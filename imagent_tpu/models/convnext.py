"""Flax ConvNeXt family (tiny/small/base/large), NHWC, TPU-native.

A modern post-reference family (the reference hard-codes resnet18,
``imagenet.py:312``): ConvNeXt ("A ConvNet for the 2020s") replaces
BatchNorm with LayerNorm, bottlenecks with inverted depthwise blocks,
and ReLU with GELU. The architecture matches torchvision's
``convnext_{tiny,small,base,large}`` exactly — stem 4x4/s4 conv +
LayerNorm, stage transitions LayerNorm + 2x2/s2 conv, blocks
[depthwise 7x7 -> LayerNorm -> Linear 4x -> GELU -> Linear] with a
1e-6-initialized per-channel layer scale, eps=1e-6 everywhere,
truncated-normal(0.02) init — so parameter counts line up with the
published numbers:

    convnext_tiny: 28,589,128    convnext_small: 50,223,688
    convnext_base: 88,591,464    convnext_large: 197,767,336

TPU-first choices: the network is channels-last END TO END — torch
permutes NCHW<->NHWC around every block's LayerNorm/Linear pair; here
NHWC is the native layout, LayerNorm reduces over the minor (lane)
dimension and the two MLP projections are plain ``nn.Dense`` on the
last axis, so no transposes exist anywhere in the program. The
depthwise 7x7 lowers via ``feature_group_count=C`` (cg=1: pure
HBM-streaming by the grouped-conv roofline in docs/ROOFLINE.md — its
49 taps/channel give it ~5.4x the arithmetic intensity of a 3x3
depthwise (49/9), which is why the geometry works on TPUs at all). GELU uses
``approximate=False`` for torch-exact numerics. No BatchNorm means no
``batch_stats`` collection: the train/eval steps already handle
stat-less models via the ViT path, and there is nothing for EMA's
``ema_batch_stats`` to track (params-only EMA is exact here).

Stochastic depth (``drop_path_rate``, torchvision's
``stochastic_depth_prob``) is implemented with per-block linearly
scaled drop probability and per-sample ("row") masks, but defaults to
0.0 and is a LIBRARY-level knob: enabling it requires passing
``rngs={"droppath": key}`` to ``apply`` — the production train step
(train.make_train_step) applies without rngs and therefore supports
rate 0.0 only. ``tests/test_models.py`` covers both modes.

``fused_mlp`` ("auto"|"on"|"off", the --fused-mlp flag) selects the
Pallas fused lowering of each block's LN -> C->4C -> GELU -> 4C->C ->
layer-scale -> residual chain (``ops/fused_mlp.py``: the 4C
intermediate stays in VMEM instead of round-tripping HBM; custom VJP
recomputes it in the backward). The parameter tree is IDENTICAL in all
three modes — the fused path reads the same ``norm``/``pwconv1``/
``pwconv2``/``layer_scale`` leaves the unfused modules own — so
checkpoints, EMA, torch import/export, and sharding specs are
unaffected. "auto" fuses only where the backward working set fits VMEM
and the backend is TPU; "on" forces the kernel (interpret mode off-TPU
— how CI exercises it) but still falls back on VMEM overflow; an
active drop-path mask always falls back (the fused chain is the
production rate-0.0 block). "off" is bit-for-bit today's path.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# torchvision ConvNeXt init: trunc_normal_(std=0.02) on every conv and
# linear weight, zero biases.
trunc_init = nn.initializers.truncated_normal(stddev=0.02)


class ConvNeXtBlock(nn.Module):
    """Inverted depthwise block: dw7x7 -> LN -> 4x MLP -> layer scale.

    ``drop_prob`` is this block's stochastic-depth probability (already
    linearly scaled by the caller); when active the whole residual
    branch is dropped per-sample and the kept samples are scaled by
    1/(1-p) (torchvision ``stochastic_depth(mode="row")``)."""

    dim: int
    drop_prob: float = 0.0
    dtype: jnp.dtype = jnp.float32
    fused_mlp: str = "off"  # auto|on|off (ops/fused_mlp.py lowering)

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.Conv(self.dim, (7, 7), padding=((3, 3), (3, 3)),
                    feature_group_count=self.dim, use_bias=True,
                    dtype=self.dtype, kernel_init=trunc_init,
                    name="dwconv")(x)
        gamma = self.param("layer_scale",
                           nn.initializers.constant(1e-6), (self.dim,))
        from imagent_tpu.ops.fused_mlp import (
            fused_block_rows, fused_mlp_block,
        )
        dropping = self.drop_prob > 0.0 and train
        block_rows = fused_block_rows(self.fused_mlp, self.dim,
                                      dtype=self.dtype, dropping=dropping)
        if block_rows is not None and not self.is_initializing():
            # Fused lowering: LN -> MLP -> layer-scale -> residual in
            # one Pallas pass, reading the SAME param leaves the
            # unfused modules below own (created at init, which always
            # runs the unfused path) — the tree never changes.
            p_norm = self.get_variable("params", "norm")
            p1 = self.get_variable("params", "pwconv1")
            p2 = self.get_variable("params", "pwconv2")
            return fused_mlp_block(
                x, y, p_norm["scale"], p_norm["bias"],
                p1["kernel"], p1["bias"], p2["kernel"], p2["bias"],
                gamma, eps=1e-6, block_rows=block_rows)
        y = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm")(y)
        y = nn.Dense(4 * self.dim, dtype=self.dtype,
                     kernel_init=trunc_init, name="pwconv1")(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, dtype=self.dtype,
                     kernel_init=trunc_init, name="pwconv2")(y)
        y = y * gamma.astype(self.dtype)
        if dropping:
            keep = 1.0 - self.drop_prob
            mask = jax.random.bernoulli(
                self.make_rng("droppath"), keep,
                (x.shape[0],) + (1,) * (x.ndim - 1))
            y = y * (mask.astype(y.dtype) / keep)
        return x + y


class ConvNeXt(nn.Module):
    """torchvision-plan ConvNeXt on NHWC inputs."""

    depths: Sequence[int]
    dims: Sequence[int]
    num_classes: int = 1000
    drop_path_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    remat: bool = False  # jax.checkpoint each block on backward
    fused_mlp: str = "off"  # auto|on|off Pallas block lowering

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.dims[0], (4, 4), (4, 4), padding="VALID",
                    use_bias=True, dtype=self.dtype,
                    kernel_init=trunc_init, name="stem_conv")(x)
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype,
                         name="stem_norm")(x)
        block_cls = nn.remat(ConvNeXtBlock) if self.remat else ConvNeXtBlock
        total = sum(self.depths)
        block_id = 0
        for i, (depth, dim) in enumerate(zip(self.depths, self.dims)):
            if i > 0:
                x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype,
                                 name=f"downsample{i}_norm")(x)
                x = nn.Conv(dim, (2, 2), (2, 2), padding="VALID",
                            use_bias=True, dtype=self.dtype,
                            kernel_init=trunc_init,
                            name=f"downsample{i}_conv")(x)
            for j in range(depth):
                # torchvision: sd_prob = rate * block_id / (total - 1)
                p = (self.drop_path_rate * block_id / max(total - 1, 1))
                x = block_cls(dim=dim, drop_prob=p, dtype=self.dtype,
                              fused_mlp=self.fused_mlp,
                              name=f"stage{i}_block{j}")(x, train=train)
                block_id += 1
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = x.astype(jnp.float32)  # head in fp32, like the other families
        x = nn.LayerNorm(epsilon=1e-6, name="head_norm")(x)
        x = nn.Dense(self.num_classes, kernel_init=trunc_init,
                     name="head")(x)
        return x


# (depths, dims) per arch — torchvision's constructor table.
CONVNEXT_DEFS = {
    "convnext_tiny": ((3, 3, 9, 3), (96, 192, 384, 768)),
    "convnext_small": ((3, 3, 27, 3), (96, 192, 384, 768)),
    "convnext_base": ((3, 3, 27, 3), (128, 256, 512, 1024)),
    "convnext_large": ((3, 3, 27, 3), (192, 384, 768, 1536)),
}

CONVNEXT_REGISTRY = {
    name: partial(ConvNeXt, depths=depths, dims=dims)
    for name, (depths, dims) in CONVNEXT_DEFS.items()
}

# torchvision published param counts at 1000 classes.
CONVNEXT_PARAM_COUNTS = {
    "convnext_tiny": 28_589_128,
    "convnext_small": 50_223_688,
    "convnext_base": 88_591_464,
    "convnext_large": 197_767_336,
}
