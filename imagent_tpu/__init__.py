"""imagent_tpu — a TPU-native distributed ImageNet training framework.

A ground-up JAX/XLA re-design of the capability surface of
``Abdoulaye-Koroko/Imagent-distributed-training-pytorch-with-slurm``
(reference mounted at ``/root/reference``): Slurm-launched multi-host
synchronous data-parallel ImageNet classification with collective
gradient reduction, distributed data sharding, cross-rank metric
reduction, LR scheduling, TensorBoard logging and best-model
checkpointing (reference: ``imagenet.py:1-453``, ``imagenet.sh:1-27``).

TPU-first architecture:

* SPMD over a ``jax.sharding.Mesh`` (``data`` x ``model`` axes) instead of
  one-process-per-GPU DDP (``imagenet.py:316``).
* One jit-compiled train step: forward, loss, grad, ``psum``-mean of
  gradients *and* metrics — collapsing the reference's per-step
  3 scalar allreduces + device sync (``imagenet.py:137-148``).
* ``jax.distributed.initialize()`` (PJRT coordination service) instead of
  the ``env://`` TCP rendezvous (``imagenet.py:237-273``).
* Per-host sharded input pipeline instead of ``DistributedSampler``
  (``imagenet.py:346-359``).
"""

__version__ = "0.1.0"

from imagent_tpu.config import Config, parse_args  # noqa: F401
