"""Configuration: CLI flags + typed dataclass.

Keeps the reference's argparse surface (``imagenet.py:433-452``):
``--seed --backend --batch-size --epochs --lr --save-model``, and promotes
its hard-coded constants to flags with reference defaults (image size 448
at ``imagenet.py:281``, normalize constants ``imagenet.py:283``, data root
``imagenet.py:287-289``, momentum/weight-decay ``imagenet.py:325``, LR step
decay /10 every 30 epochs ``imagenet.py:154-162``, workers ``imagenet.py:352``,
TensorBoard dir / checkpoint path ``imagenet.py:363,392``, arch
``imagenet.py:312``).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Sequence


@dataclasses.dataclass
class Config:
    # ---- reference flag surface (imagenet.py:435-450) ----
    seed: int = 0
    backend: str = "tpu"  # PJRT platform: tpu|cpu|gpu (reference: nccl|gloo)
    batch_size: int = 128  # per data-parallel replica, as in the reference
    epochs: int = 100
    lr: float = 0.1
    save_model: bool = False

    # ---- promoted hard-coded constants (reference defaults) ----
    arch: str = "resnet18"  # imagenet.py:312
    image_size: int = 448  # imagenet.py:281
    num_classes: int = 1000
    mean: Sequence[float] = (0.5, 0.5, 0.5)  # imagenet.py:283
    std: Sequence[float] = (0.5, 0.5, 0.5)  # imagenet.py:283
    data_root: str = "../data/imagenet"  # imagenet.py:287-289
    momentum: float = 0.9  # imagenet.py:325
    weight_decay: float = 1e-4  # imagenet.py:325
    # sgd (reference parity) | nadam (the optimizer the reference's dead
    # `custom_optimizers` import pointed at, imagenet.py:36) | adamw |
    # lars (large-batch SGD).
    optimizer: str = "sgd"
    lr_decay_period: int = 30  # imagenet.py:158
    lr_decay_factor: float = 0.1  # imagenet.py:158
    workers: int = 10  # imagenet.py:352 (0 = in-process serial decode)
    native_io: bool = True  # C++ threaded decode (imagent_tpu/native)
    # Decode-offload endpoints, "host:port[,host:port...]" ("" = off):
    # non-training CPU hosts running `python -m imagent_tpu.data.serve`
    # decode this run's batches (same stream contract, shared-nothing)
    # and ship ready uint8 batches over the wire to the staging queue
    # (data/offload.py). A dead/unreachable service degrades to local
    # decode with a counted fallback, never a dead run. imagefolder/tar
    # datasets only.
    decode_offload: str = ""
    # Alert when an epoch's input-wait fraction (step-loop time blocked
    # on the staging queue / epoch wall) exceeds this: master WARN +
    # `input_wait_alert` telemetry event + status.json surface, with
    # the slowest host named via the pod straggler flags (ROADMAP item
    # 5's alerting clause). 0 disables.
    input_wait_alert: float = 0.10
    log_dir: str = "runs/imagent_tpu"  # imagenet.py:363
    ckpt_dir: str = "checkpoints"  # imagenet.py:392 (file → dir for Orbax)

    # ---- new capabilities (absent in reference) ----
    resume: bool = False  # full-state resume (reference has none, SURVEY §5)
    # Run validation only (on the resumed/initialized params), no training.
    eval_only: bool = False
    # Initialize params from a torch .pt state_dict (the reference's
    # checkpoint format, imagenet.py:392, DDP "module." prefix handled) —
    # converted via compat/torch_weights.py. ResNet + ViT +
    # ConvNeXt archs.
    init_from_torch: str = ""
    # Write the final params as a torchvision-named torch .pt
    # state_dict at run end (the inverse of --init-from-torch; all
    # three families) — train here, serve/analyze in torch.
    export_torch: str = ""
    # RandomResizedCrop + hflip train augmentation. The reference has NONE
    # (SURVEY §0: Resize+Normalize only, hence its 63% top-1); required for
    # the north-star accuracy config (BASELINE.md).
    augment: bool = False
    dataset: str = "imagefolder"  # imagefolder | tar | synthetic
    synthetic_size: int = 2048  # images per epoch in synthetic mode
    bf16: bool = True  # bfloat16 compute on the MXU
    # Wire dtype of image batches, decode → IPC → prefetch queue → H2D
    # (data/pipeline.py Batch contract). All three carry the RAW
    # [0, 255] pixel scale — dequantize+normalize run in-graph — so
    # this knob changes bytes on the wire and nothing else:
    #   uint8   (default) 1 byte/pixel, 4× leaner than the reference's
    #           host-normalized float32 path (imagenet.py:280-283);
    #   bf16    2 bytes/pixel (the old --input-bf16 behavior's slot);
    #   float32 4 bytes/pixel, the A/B parity reference.
    transfer_dtype: str = "uint8"
    # Device prefetch staging depth (data/prefetch.py): how many global
    # batches are staged on-device ahead of the running step. 2 = double
    # buffering; deeper only adds HBM pressure unless H2D is bursty.
    prefetch_depth: int = 2
    warmup_epochs: int = 0  # linear LR warmup (0 = reference behavior)
    label_smoothing: float = 0.0  # CE smoothing (0 = reference behavior)
    # In-graph batch augmentation (ops/mixing.py): Beta(a, a) mixing
    # strength; 0 = off = reference behavior. Both > 0 = coin flip per
    # batch between the two modes.
    mixup: float = 0.0
    cutmix: float = 0.0
    # Parameter EMA maintained inside the train step; eval runs on the
    # averaged weights when > 0 (train.TrainState.ema_params).
    ema_decay: float = 0.0
    # In-graph photometric jitter (ops/jitter.py): brightness /
    # contrast / saturation strengths, torchvision factor semantics.
    # All 0 = off = reference behavior.
    color_jitter: Sequence[float] = (0.0, 0.0, 0.0)
    # jax.checkpoint each residual/encoder block: recompute activations
    # on the backward pass — ~33% more FLOPs for O(depth) less HBM.
    remat: bool = False
    # ResNet stem variant: "v1" (torchvision-exact 7x7/s2; required for
    # --init-from-torch) or "s2d" (MLPerf-style space-to-depth 4x4/s1
    # stem — measured lever table in docs/ROOFLINE.md).
    stem: str = "v1"
    # Micro-batches accumulated per optimizer step inside the compiled
    # train step: effective global batch = batch_size * data_parallel * K.
    grad_accum: int = 1
    schedule: str = "step"  # step | cosine
    eval_every: int = 1  # validate every N epochs
    log_every: int = 50  # step-level stdout cadence on process 0
    # Whole-run jax.profiler trace (SURVEY §5 tracing). Prefer
    # --profile-at-step: a full-run trace of a long job is unloadably
    # large and mostly steady-state repetition.
    profile: bool = False
    # ---- telemetry (imagent_tpu/telemetry/) ----
    # Goodput accounting + step-time percentiles + pod aggregation,
    # written as TB scalars and runs/<run>/telemetry.jsonl. On by
    # default: the per-step cost is two host timestamps (no device
    # syncs); --no-telemetry is the kill switch.
    telemetry: bool = True
    # Capture a jax.profiler trace for M global steps starting at step
    # N ("N" or "N:M", M defaults to 10). Resume-aware: global step =
    # epoch * steps_per_epoch + step. Mutually exclusive with
    # --profile.
    profile_at_step: str = ""
    # A host is flagged as a straggler when its per-epoch input-wait or
    # step-time p95 exceeds this multiple of the pod median (see
    # telemetry/aggregate.py for the absolute floors).
    straggler_factor: float = 2.0
    # Persistent XLA compilation cache dir ("" = off): restarted/resumed
    # runs skip the first-step compile (~minutes for big models).
    compile_cache: str = ""
    # One-compile AOT startup (compilecache.py): compile each step
    # executable once via lower().compile(), share it with the chip
    # accountant, and (with --compile-cache) serialize it for warm
    # restarts. False = legacy jit-on-first-step.
    aot_steps: bool = True
    check_nans: bool = False  # debug flag (SURVEY §5 sanitizers)
    # Asynchronous per-epoch LAST checkpointing (checkpoint.save_async):
    # the step loop blocks only for the device→host snapshot;
    # serialization + rotation + manifest hashing run on a background
    # committer thread whose verdict is pod-agreed at the next epoch
    # boundary. --no-async-ckpt restores the fully synchronous save —
    # the bench-smoke baseline the telemetry regression compares
    # against.
    async_ckpt: bool = True
    # Checkpoint format family. "snapshot" (default): DP/replicated
    # states use the flat snapshot format and host-sharded states
    # (multi-host FSDP/TP/ZeRO-1) the SHARDED snapshot format — both
    # collective-free on the commit path, both restorable onto any
    # topology. "orbax" is the legacy escape hatch: sharded states go
    # through the collective Orbax gather/save (no emergency salvage,
    # no cross-topology sharded resume) — keep only for reading back
    # with external Orbax tooling.
    ckpt_format: str = "snapshot"

    # ---- model-health observability (telemetry/health.py) ----
    # In-graph health stats: the train step appends global grad-norm,
    # param-norm and update-ratio to the replicated metric vector
    # (train.HEALTH_FIELDS), consumed on the lagged frontier — zero
    # added host syncs. --no-health-stats is the kill switch.
    health_stats: bool = True
    # Divergence early-warning: an observation exceeding this factor x
    # its trailing EWMA baseline (grad-norm and update-ratio) is a
    # health anomaly — warned, logged as a health_anomaly telemetry
    # event, and (with --health-rollback) fed to the rollback
    # machinery BEFORE the non-finite guard can fire. 0 disables.
    health_grad_spike: float = 10.0
    # Same, for the per-step train loss. Deliberately loose: 3-4x loss
    # excursions are routine in early training (measured on the CPU
    # drill geometry); a 10x spike over the trailing EWMA is a
    # genuinely diverging run, not noise.
    health_loss_spike: float = 10.0
    # Clean steps the EWMA baselines must absorb before any verdict.
    health_warmup_steps: int = 20
    # Roll back to the last good checkpoint on a health anomaly (off =
    # warn + telemetry only).
    health_rollback: bool = False
    # Crash flight recorder (telemetry/flightrec.py): ring of the last
    # N lagged step/health records, flushed as
    # <log_dir>/flightrec.<rank>.json on every fatal exit path and
    # referenced from the tombstone. 0 disables.
    flightrec_steps: int = 256

    # ---- SLO engine + OpenMetrics exporter ----
    # Declarative run-health objectives (telemetry/slo.py), evaluated
    # against every epoch's telemetry record on the master: "off"
    # (default), "default" (the built-in production spec), or a JSON
    # spec file path. Breaches become slo_breach telemetry events, TB
    # markers, status.json fields and loud prints; `python -m
    # imagent_tpu.telemetry slo <run_dir>` replays the evaluation
    # offline (`make slo-check`).
    slo: str = "off"
    # Live OpenMetrics/Prometheus endpoint (telemetry/export.py):
    # process 0 serves GET /metrics on this port with goodput phases,
    # step percentiles, health EWMAs, HBM, pod/per-peer heartbeat
    # state, checkpoint commit geometry, SLO breach counters and
    # compile-event counts — refreshed at epoch boundaries (the same
    # state status.json records). 0 = off.
    metrics_port: int = 0
    # Chip accountant (telemetry/chipacct.py): capture the compiled
    # step's XLA cost/memory analyses once at startup, attribute the
    # TrainState's per-device bytes by component, derive zero-step-cost
    # MFU from the goodput partition, and run the OOM preflight (a
    # modeled peak over the known HBM limit refuses the run with
    # fatal-config exit 78 before step 0). Costs one extra startup
    # compile per captured executable (AOT products don't land in the
    # jit cache); False skips capture AND the preflight.
    chipacct: bool = True
    # Preflight HBM budget override, GiB per device: stands in where
    # the backend reports no memory limit (CPU) or the operator wants
    # a tighter envelope than the hardware's. 0 = use
    # device.memory_stats() when available, else preflight reports
    # "unknown-limit" and never refuses.
    hbm_budget_gb: float = 0.0
    # Peak bf16 TFLOP/s per chip for the MFU ratio, overriding the
    # utils/flops.py device-kind registry — for kinds the registry
    # does not know (new hardware, CPU test runs). 0 = registry only;
    # unknown kinds then report achieved TFLOP/s without an MFU ratio.
    peak_tflops: float = 0.0

    # ---- pod tracer (telemetry/trace.py) ----
    # Cross-host span timeline: every subsystem (engine phases,
    # checkpoint snapshot/commit/restore, staging-queue waits, offload
    # requests, deadman verdicts) emits spans into per-thread rings,
    # flushed as runs/<run>/trace/trace.<rank>.jsonl at each epoch
    # boundary and on every fatal ramp; `python -m imagent_tpu
    # .telemetry trace <run_dir>` merges them into one skew-corrected
    # Perfetto-loadable trace.json. "phases" coalesces per-step
    # dispatches into windows; "steps" records every dispatch
    # individually (one span per optimizer step). Off by default: off
    # means NO recorder — zero files, zero ring cost.
    trace: str = "off"
    # Spans kept per thread between flushes (oldest dropped, counted).
    trace_buffer: int = 4096

    # ---- resilience (imagent_tpu/resilience/) ----
    # Non-finite step guard: bad steps are always skipped in-graph
    # (train.py); after this many CONSECUTIVE skipped steps the engine
    # rolls the state back to the last restorable checkpoint and
    # replays (0 disables the rollback policy, not the skip).
    max_bad_steps: int = 3
    # Step-progress watchdog: if no train step completes within this
    # many seconds (hung collective, wedged input pipeline), dump
    # all-thread stacks and checkpoint-and-exit like a preemption
    # (0 = off).
    watchdog_secs: float = 0.0
    # Rotated fallback copies of the LAST checkpoint (last.1..last.K)
    # kept for the integrity-verified restore chain LAST -> previous
    # LASTs -> BEST. 0 = single-slot legacy behavior.
    keep_last_k: int = 1
    # Fault-injection drills: arm named fault points, e.g.
    # "nan-grads:after=4;times=4,stall-step:secs=6"
    # (resilience/faultinject.py; also via IMAGENT_FAULTS env var).
    faults: str = ""
    # Out-of-band partial-pod-failure detection (resilience/heartbeat +
    # deadman): each host writes a heartbeat record to
    # <log_dir>/heartbeats/ and monitors its peers with NO collectives;
    # a peer stale past this deadline (or leaving a fatal tombstone)
    # degrades the pod — emergency snapshot, retryable exit, launcher
    # requeue onto --resume. 0 = off. Must be >= 2x --heartbeat-secs.
    peer_deadline_secs: float = 0.0
    # Heartbeat write cadence for the mesh above.
    heartbeat_secs: float = 2.0
    # Elastic pod (imagent_tpu/elastic.py): when a peer dies the
    # deadman verdict becomes CONTINUE — survivors land the salvage
    # snapshot and re-initialize as a SMALLER mesh over the pod-agreed
    # roster (shrink-to-survive); a relaunch with the replacement host
    # present re-expands (grow-on-requeue), and a waiting host's join
    # request stops the running pod at a pod-agreed step to re-form.
    # Requires --global-batch (the optimization trajectory must not
    # follow the world size) and the plain data-parallel path. Implies
    # resume-if-checkpoint-exists so every rendezvoused attempt agrees
    # on the restore.
    elastic: bool = False
    # Fixed GLOBAL optimization batch, decoupled from world size:
    # per-host batch x grad-accum is recomputed as
    # global_batch / (batch_size x data_parallel_size) on every
    # (re)start, so a resize changes gradient-accumulation depth, not
    # the loss trajectory. 0 = legacy behavior (global batch =
    # batch_size x dp x grad_accum). Must be divisible by
    # batch_size x dp at every world size the pod may shrink/grow to.
    global_batch: int = 0
    # Elastic rendezvous settle window: the roster leader commits the
    # partial join set after this long with no new joiner (a full
    # world commits immediately). Bounds how long a resize waits for
    # a slow host before excluding it (it becomes a grow request).
    elastic_settle_secs: float = 10.0

    # ---- mesh geometry / parallelism strategies ----
    # Data-parallel size is inferred (devices / model_parallel). A model axis
    # is first-class in the mesh design (SURVEY §2c disposition) even though
    # the parity workload only uses the data axis.
    model_parallel: int = 1
    # Mesh-axis shorthand (the production spelling for model-axis pods):
    # --tp N == --tensor-parallel --model-parallel N; --pp N ==
    # --pipeline-parallel N; --dp N asserts the resulting data-parallel
    # degree (refused loudly on mismatch instead of silently resharding).
    # 0 = unset; the engine resolves these into the legacy fields before
    # any validation, and refuses mixed spellings.
    tp: int = 0
    pp: int = 0
    dp: int = 0
    # Sequence parallelism over the model axis (ViT only):
    # none | ring (ring attention) | ulysses (all-to-all head exchange).
    seq_parallel: str = "none"
    # Megatron-style tensor parallelism over the model axis (ViT only):
    # heads + MLP hidden shard across chips (parallel/tensor_parallel.py).
    tensor_parallel: bool = False
    # GPipe pipeline parallelism over the pipe axis: ViT encoder layers
    # split into stages (any S), or the ResNet conv stages (S=2),
    # microbatches streamed via ppermute (parallel/pipeline.py,
    # parallel/resnet_pipeline.py). On ViT composes with
    # --tensor-parallel,
    # --seq-parallel ring|ulysses, and (at --moe-every 1)
    # --expert-parallel — 3-D mesh in every case.
    pipeline_parallel: int = 1
    microbatches: int = 1  # GPipe microbatches per step (pipeline path)
    # Mixture-of-Experts (ViT only): every k-th block's MLP becomes a
    # Switch-routed expert bank (parallel/expert_parallel.py); with
    # --expert-parallel the experts shard over the model axis (GShard
    # all_to_all dispatch).
    moe_every: int = 0
    num_experts: int = 8
    capacity_factor: float = 1.25
    expert_parallel: bool = False
    moe_aux_weight: float = 0.01  # Switch load-balancing loss weight
    moe_top_k: int = 1  # router choices per token (1=Switch, 2=GShard)
    # FSDP (ZeRO-3): params + momentum fully sharded over the data axis
    # via the XLA SPMD partitioner (parallel/fsdp.py) — plain jit with
    # shardings, XLA inserts per-layer all-gathers/reduce-scatters.
    fsdp: bool = False
    # ZeRO-1: shard the SGD momentum buffer over the data axis
    # (parallel/zero.py) — 1/dp optimizer memory per chip, numerically
    # identical updates. Data-parallel path only.
    zero1: bool = False
    # Capacity groups for the dense (non-EP) MoE path. The dispatch
    # tensors are [T/G, E, C] per group with C ~ cf*T/(G*E): more groups
    # = quadratically less dispatch memory. Under --expert-parallel the
    # group count is the expert-axis size and this is ignored.
    moe_groups: int = 8
    # Single-chip attention kernel (ViT only): full (XLA einsum) | flash
    # (Pallas fused kernel, ops/flash_attention.py).
    attn: str = "full"
    # ConvNeXt block lowering (ops/fused_mlp.py): Pallas-fused
    # LN -> C->4C -> GELU -> 4C->C -> layer-scale -> residual with the
    # 4C intermediate VMEM-resident (never written to HBM) and a
    # custom VJP that recomputes it in the backward. "auto" fuses only
    # where the backward working set fits VMEM and the backend is TPU;
    # "on" forces the kernel (interpret off-TPU; VMEM overflow still
    # falls back); "off" (default, opt-in pending the hardware verdict
    # in docs/ROOFLINE.md) is bit-for-bit today's path.
    fused_mlp: str = "off"
    # ViT perf/regularization levers (models/vit.py): one-GEMM QKV
    # projection (same param tree) and DINOv2-style register tokens
    # (appended, excluded from readout; 59 fills 224px ViT-B/16's 197
    # tokens to the 256-lane MXU tile).
    fused_qkv: bool = False
    register_tokens: int = 0

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native distributed ImageNet training (imagent_tpu)"
    )
    c = Config()
    # Reference flag names kept verbatim (imagenet.py:435-450).
    p.add_argument("--seed", type=int, default=c.seed, help="random seed")
    p.add_argument("--backend", type=str, default=c.backend,
                   help="PJRT platform: tpu|cpu|gpu")
    p.add_argument("--batch-size", type=int, default=c.batch_size,
                   help="per-replica batch size (default: 128)")
    p.add_argument("--epochs", type=int, default=c.epochs,
                   help="number of epochs to train (default: 100)")
    p.add_argument("--lr", type=float, default=c.lr,
                   help="initial learning rate (default: 0.1)")
    p.add_argument("--save-model", action="store_true", default=False,
                   help="save best checkpoint on val top-1 improvement")
    # Promoted constants.
    p.add_argument("--arch", type=str, default=c.arch,
                   choices=["resnet18", "resnet34", "resnet50",
                            "resnet101", "resnet152", "resnext50_32x4d",
                            "resnext101_32x8d", "wide_resnet50_2",
                            "wide_resnet101_2", "vit_b16", "vit_l16",
                            "vit_h14", "vit_debug", "convnext_tiny",
                            "convnext_small", "convnext_base",
                            "convnext_large"])
    p.add_argument("--image-size", type=int, default=c.image_size)
    p.add_argument("--num-classes", type=int, default=c.num_classes)
    p.add_argument("--data-root", type=str, default=c.data_root)
    p.add_argument("--momentum", type=float, default=c.momentum)
    p.add_argument("--weight-decay", type=float, default=c.weight_decay)
    p.add_argument("--optimizer", type=str, default=c.optimizer,
                   choices=["sgd", "nadam", "adamw", "lars", "lamb"])
    p.add_argument("--lr-decay-period", type=int, default=c.lr_decay_period)
    p.add_argument("--lr-decay-factor", type=float, default=c.lr_decay_factor)
    p.add_argument("--workers", type=int, default=c.workers)
    p.add_argument("--no-native-io", dest="native_io", action="store_false",
                   default=True,
                   help="disable the C++ decode path (PIL fallback)")
    p.add_argument("--decode-offload", type=str, default=c.decode_offload,
                   metavar="HOST:PORT[,HOST:PORT...]",
                   help="decode-offload service endpoints (python -m "
                        "imagent_tpu.data.serve on non-training CPU "
                        "hosts); falls back to local decode when "
                        "unreachable")
    p.add_argument("--input-wait-alert", type=float,
                   default=c.input_wait_alert, metavar="FRACTION",
                   help="WARN + telemetry event + status.json alert "
                        "when an epoch's input-wait exceeds this "
                        "fraction of epoch wall (default 0.10; 0 "
                        "disables)")
    p.add_argument("--log-dir", type=str, default=c.log_dir)
    p.add_argument("--ckpt-dir", type=str, default=c.ckpt_dir)
    # New capabilities.
    p.add_argument("--resume", action="store_true", default=False)
    p.add_argument("--eval-only", action="store_true", default=False,
                   help="validate only (with --resume or "
                        "--init-from-torch), no training")
    p.add_argument("--init-from-torch", type=str, default="",
                   help="torch .pt state_dict to convert and load "
                        "(the reference's checkpoint format)")
    p.add_argument("--export-torch", type=str, default="",
                   help="write the final params as a torchvision-named "
                        "torch .pt state_dict (inverse of "
                        "--init-from-torch)")
    p.add_argument("--augment", action="store_true", default=False,
                   help="RandomResizedCrop+hflip train augmentation "
                        "(reference parity is OFF)")
    p.add_argument("--dataset", type=str, default=c.dataset,
                   choices=["imagefolder", "tar", "synthetic"],
                   help="tar = {train,val}/*.tar shards (webdataset-style "
                        "class-dir members)")
    p.add_argument("--synthetic-size", type=int, default=c.synthetic_size)
    p.add_argument("--no-bf16", dest="bf16", action="store_false",
                   default=True)
    p.add_argument("--transfer-dtype", type=str, default=c.transfer_dtype,
                   choices=["uint8", "bf16", "float32"],
                   help="image wire dtype host->device; all carry raw "
                        "[0,255] values, normalization is in-graph "
                        "(uint8 = 4x leaner than float32)")
    p.add_argument("--input-bf16", dest="transfer_dtype",
                   action="store_const", const="bf16",
                   default=argparse.SUPPRESS,
                   help="deprecated alias for --transfer-dtype bf16")
    p.add_argument("--prefetch-depth", type=int, default=c.prefetch_depth,
                   help="device prefetch staging depth (default 2 = "
                        "double buffering)")
    p.add_argument("--warmup-epochs", type=int, default=c.warmup_epochs)
    p.add_argument("--label-smoothing", type=float,
                   default=c.label_smoothing)
    p.add_argument("--mixup", type=float, default=c.mixup,
                   help="MixUp Beta(a,a) strength, in-graph (0 = off)")
    p.add_argument("--cutmix", type=float, default=c.cutmix,
                   help="CutMix Beta(a,a) strength, in-graph (0 = off)")
    p.add_argument("--ema-decay", type=float, default=c.ema_decay,
                   help="parameter EMA decay; eval uses the averaged "
                        "weights (0 = off)")
    p.add_argument("--color-jitter", type=float, nargs=3,
                   default=list(c.color_jitter),
                   metavar=("BRIGHTNESS", "CONTRAST", "SATURATION"),
                   help="in-graph photometric jitter strengths "
                        "(torchvision semantics; 0 0 0 = off)")
    p.add_argument("--remat", action="store_true", default=False,
                   help="rematerialize blocks on backward (less HBM)")
    p.add_argument("--stem", default=c.stem, choices=["v1", "s2d"],
                   help="ResNet stem: torchvision 7x7/s2 or "
                        "space-to-depth 4x4/s1 (docs/ROOFLINE.md)")
    p.add_argument("--grad-accum", type=int, default=c.grad_accum,
                   help="micro-batches per optimizer step (default 1)")
    p.add_argument("--schedule", type=str, default=c.schedule,
                   choices=["step", "cosine"])
    p.add_argument("--eval-every", type=int, default=c.eval_every)
    p.add_argument("--log-every", type=int, default=c.log_every)
    p.add_argument("--profile", action="store_true", default=False,
                   help="whole-run jax.profiler trace into --log-dir "
                        "(prefer --profile-at-step for long runs)")
    p.add_argument("--profile-at-step", type=str,
                   default=c.profile_at_step, metavar="N[:M]",
                   help="capture a jax.profiler trace for M steps "
                        "(default 10) starting at global step N — "
                        "mid-run and resume-aware, unlike --profile")
    p.add_argument("--no-telemetry", dest="telemetry",
                   action="store_false", default=True,
                   help="disable goodput/step-time/straggler telemetry "
                        "(TB scalars + telemetry.jsonl)")
    p.add_argument("--straggler-factor", type=float,
                   default=c.straggler_factor,
                   help="flag a host whose input-wait or step p95 "
                        "exceeds this multiple of the pod median")
    p.add_argument("--compile-cache", type=str, default=c.compile_cache,
                   help="persistent XLA compilation cache directory "
                        "(also arms the serialized AOT executable "
                        "store under <dir>/aot — see "
                        "python -m imagent_tpu.compilecache)")
    p.add_argument("--no-aot-steps", dest="aot_steps",
                   action="store_false", default=c.aot_steps,
                   help="disable the one-compile AOT startup path "
                        "(step executables jit on first dispatch; "
                        "chipacct pays its own capture compile)")
    p.add_argument("--check-nans", action="store_true", default=False)
    p.add_argument("--async-ckpt", dest="async_ckpt",
                   action="store_true", default=True,
                   help="commit per-epoch LAST checkpoints on a "
                        "background thread (snapshot-then-commit; "
                        "the default)")
    p.add_argument("--no-async-ckpt", dest="async_ckpt",
                   action="store_false",
                   help="fully synchronous checkpoint saves (the "
                        "step loop stalls for serialize+commit+"
                        "manifest)")
    p.add_argument("--ckpt-format", type=str, default=c.ckpt_format,
                   choices=["snapshot", "orbax"],
                   help="checkpoint format family: snapshot = "
                        "collective-free flat/sharded snapshot formats "
                        "(emergency salvage + any-topology resume); "
                        "orbax = legacy collective Orbax for sharded "
                        "states (escape hatch)")
    # Model-health observability.
    p.add_argument("--no-health-stats", dest="health_stats",
                   action="store_false", default=True,
                   help="disable the in-graph grad/param-norm + "
                        "update-ratio metric tail and the divergence "
                        "early-warning detector")
    p.add_argument("--health-grad-spike", type=float,
                   default=c.health_grad_spike,
                   help="anomaly when grad-norm or update-ratio "
                        "exceeds this factor x its trailing EWMA "
                        "baseline (0 disables)")
    p.add_argument("--health-loss-spike", type=float,
                   default=c.health_loss_spike,
                   help="anomaly when the train loss exceeds this "
                        "factor x its EWMA baseline (loose by design: "
                        "3-4x excursions are normal early training; "
                        "0 disables)")
    p.add_argument("--health-warmup-steps", type=int,
                   default=c.health_warmup_steps,
                   help="clean steps the health baselines absorb "
                        "before any anomaly verdict")
    p.add_argument("--health-rollback", action="store_true",
                   default=False,
                   help="roll back to the last good checkpoint on a "
                        "health anomaly (divergence caught BEFORE the "
                        "non-finite guard; default: warn only)")
    p.add_argument("--flightrec-steps", type=int,
                   default=c.flightrec_steps,
                   help="flight-recorder ring size: last N lagged "
                        "step/health records flushed as "
                        "flightrec.<rank>.json on fatal exits "
                        "(0 disables)")
    # SLO engine + OpenMetrics exporter.
    p.add_argument("--slo", type=str, default=c.slo, metavar="SPEC",
                   help="declarative run-health SLOs evaluated at "
                        "every epoch boundary (telemetry/slo.py): "
                        "'off', 'default' (built-in spec), or a JSON "
                        "spec file; breaches become slo_breach "
                        "events, TB markers, status.json fields and "
                        "loud prints")
    p.add_argument("--metrics-port", type=int, default=c.metrics_port,
                   help="serve live OpenMetrics/Prometheus text on "
                        "this port from process 0 (GET /metrics; "
                        "goodput, step percentiles, health, pod, "
                        "ckpt, SLO and compile series; 0 = off)")
    # Chip accountant + OOM preflight.
    p.add_argument("--no-chipacct", dest="chipacct",
                   action="store_false", default=c.chipacct,
                   help="skip the startup XLA cost/memory capture, "
                        "MFU accounting and the OOM preflight "
                        "(telemetry/chipacct.py); also the bypass "
                        "for a preflight refusal")
    p.add_argument("--hbm-budget-gb", type=float,
                   default=c.hbm_budget_gb, metavar="GIB",
                   help="per-device HBM budget for the OOM preflight "
                        "when the backend reports no limit (or to "
                        "tighten it); modeled peak over budget "
                        "refuses the run with exit 78 (0 = device "
                        "limit when known, else no refusal)")
    p.add_argument("--peak-tflops", type=float, default=c.peak_tflops,
                   metavar="TFLOPS",
                   help="peak bf16 TFLOP/s per chip for the MFU "
                        "ratio, overriding the device-kind registry "
                        "(unknown kinds otherwise report achieved "
                        "TFLOP/s only; 0 = registry)")
    # Pod tracer.
    p.add_argument("--trace", type=str, default=c.trace,
                   choices=["off", "phases", "steps"],
                   help="cross-host span timeline (telemetry/trace.py)"
                        ": phases = phase boundaries + coalesced "
                        "dispatch windows, steps = every dispatch "
                        "individually; per-rank trace/trace.<rank>"
                        ".jsonl merged by `python -m imagent_tpu"
                        ".telemetry trace` into Perfetto-loadable "
                        "trace.json (off = no recorder, zero cost)")
    p.add_argument("--trace-buffer", type=int, default=c.trace_buffer,
                   help="spans kept per thread between trace flushes "
                        "(oldest dropped and counted; default 4096)")
    # Resilience subsystem.
    p.add_argument("--max-bad-steps", type=int, default=c.max_bad_steps,
                   help="consecutive non-finite (skipped) steps before "
                        "rolling back to the last good checkpoint "
                        "(0 disables rollback; the in-graph skip is "
                        "always on)")
    p.add_argument("--watchdog-secs", type=float, default=c.watchdog_secs,
                   help="step-progress watchdog deadline: dump stacks "
                        "and checkpoint-and-exit if no step completes "
                        "in this many seconds (0 = off)")
    p.add_argument("--keep-last-k", type=int, default=c.keep_last_k,
                   help="rotated fallback copies of the LAST checkpoint "
                        "for the verified restore chain (0 = one slot)")
    p.add_argument("--faults", type=str, default=c.faults,
                   help="arm fault-injection drill points, e.g. "
                        "'nan-grads:after=4;times=4' (see "
                        "resilience/faultinject.py)")
    p.add_argument("--peer-deadline-secs", type=float,
                   default=c.peer_deadline_secs,
                   help="declare a pod peer dead when its out-of-band "
                        "heartbeat is stale this long: emergency "
                        "snapshot + retryable exit for the launcher "
                        "requeue (0 = off; >= 2x --heartbeat-secs)")
    p.add_argument("--heartbeat-secs", type=float,
                   default=c.heartbeat_secs,
                   help="per-host heartbeat write cadence for the "
                        "peer deadman (default 2s)")
    p.add_argument("--elastic", action="store_true", default=False,
                   help="elastic pod: survivors of a peer death "
                        "re-form a smaller mesh and keep training "
                        "(shrink-to-survive); relaunches re-expand "
                        "(grow-on-requeue). Requires --global-batch; "
                        "DP path only; implies resume-if-checkpoint")
    p.add_argument("--global-batch", type=int, default=c.global_batch,
                   help="fixed global optimization batch, decoupled "
                        "from world size: grad-accum is derived as "
                        "global_batch/(batch_size x dp) so a resize "
                        "keeps the loss trajectory (0 = legacy "
                        "batch_size x dp x grad_accum)")
    p.add_argument("--elastic-settle-secs", type=float,
                   default=c.elastic_settle_secs,
                   help="elastic rendezvous settle window: commit the "
                        "partial roster after this long with no new "
                        "joiner (full world commits immediately)")
    p.add_argument("--model-parallel", type=int, default=c.model_parallel)
    p.add_argument("--tp", type=int, default=c.tp, metavar="N",
                   help="tensor-parallel degree (shorthand for "
                        "--tensor-parallel --model-parallel N); model "
                        "groups of N devices jointly hold one replica")
    p.add_argument("--pp", type=int, default=c.pp, metavar="N",
                   help="pipeline-parallel degree (shorthand for "
                        "--pipeline-parallel N), composable with --tp")
    p.add_argument("--dp", type=int, default=c.dp, metavar="N",
                   help="expected data-parallel degree; validated "
                        "against world size / replica size (0 = infer)")
    p.add_argument("--seq-parallel", type=str, default=c.seq_parallel,
                   choices=["none", "ring", "ulysses"])
    p.add_argument("--tensor-parallel", action="store_true", default=False,
                   help="shard attention heads + MLP over the model axis")
    p.add_argument("--pipeline-parallel", type=int, default=c.pipeline_parallel,
                   help="GPipe stages over the pipe mesh axis (ViT only)")
    p.add_argument("--microbatches", type=int, default=c.microbatches,
                   help="GPipe microbatches per step (pipeline path)")
    p.add_argument("--moe-every", type=int, default=c.moe_every,
                   help="every k-th ViT block uses a MoE MLP (0 = dense)")
    p.add_argument("--num-experts", type=int, default=c.num_experts)
    p.add_argument("--capacity-factor", type=float,
                   default=c.capacity_factor)
    p.add_argument("--expert-parallel", action="store_true", default=False,
                   help="shard MoE experts over the model axis (all_to_all)")
    p.add_argument("--moe-aux-weight", type=float, default=c.moe_aux_weight)
    p.add_argument("--moe-top-k", type=int, default=c.moe_top_k,
                   help="router choices per token (1=Switch, 2=GShard)")
    p.add_argument("--fsdp", action="store_true", default=False,
                   help="fully shard params+optimizer over the data axis "
                        "(XLA SPMD partitioner)")
    p.add_argument("--zero1", action="store_true", default=False,
                   help="shard optimizer state over the data axis (ZeRO-1)")
    p.add_argument("--moe-groups", type=int, default=c.moe_groups,
                   help="capacity groups on the dense MoE path (dispatch "
                        "memory scales as 1/groups^2)")
    p.add_argument("--attn", type=str, default=c.attn,
                   choices=["full", "flash"],
                   help="ViT attention kernel (flash = Pallas fused)")
    p.add_argument("--fused-mlp", type=str, default=c.fused_mlp,
                   choices=["auto", "on", "off"],
                   help="ConvNeXt: Pallas-fused LN->MLP->residual block "
                        "lowering, 4C intermediate kept in VMEM (auto = "
                        "fuse where the tile fits VMEM on TPU; off = "
                        "today's path)")
    p.add_argument("--fused-qkv", action="store_true",
                   default=c.fused_qkv,
                   help="ViT: one fused QKV GEMM (same param tree)")
    p.add_argument("--register-tokens", type=int,
                   default=c.register_tokens,
                   help="ViT: learned register tokens appended to the "
                        "sequence, excluded from readout (59 fills "
                        "224px ViT-B/16 to the 256-token MXU tile)")
    return p


def parse_args(argv: Sequence[str] | None = None) -> Config:
    ns = build_parser().parse_args(argv)
    fields = {f.name for f in dataclasses.fields(Config)}
    kw = {k: v for k, v in vars(ns).items() if k in fields}
    return Config(**kw)
