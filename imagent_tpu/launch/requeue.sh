#!/bin/bash
# Requeue wrapper: run the training command; when it dies with a
# RETRYABLE exit code (resilience/exitcodes.py — preemption 75,
# watchdog hard-exit 86, deadman peer-death 87, storage outage 88),
# restart it with --resume after an exponential backoff, bounded by a
# restart budget. Non-retryable codes (config errors, reproducible
# faults) and an exhausted budget exit immediately with the original
# code, so a broken invocation never crash-loops.
#
# Used as the per-task command under both launchers (slurm_tpu.sh's
# srun line, tpu_pod.sh's worker fan-out): every host of a degraded
# pod exits retryable within seconds of a peer death (the deadman
# makes the failure pod-wide and fast), so all tasks fall into this
# loop together, back off, and re-rendezvous onto --resume — the
# whole-pod requeue without scheduler support.
#
# Usage: requeue.sh <command...>
# Env knobs:
#   IMAGENT_RESTART_BUDGET   max restarts (default 3)
#   IMAGENT_RESTART_BACKOFF  base backoff seconds, doubling per
#                            restart, capped at 300 (default 5)
#   IMAGENT_RETRYABLE_CODES  space-separated override of the retryable
#                            set. The default below is a literal (this
#                            script must work when Python cannot even
#                            start) and is pinned against
#                            resilience/exitcodes.retryable_codes() by
#                            tests/test_launch.py.
set -u

BUDGET="${IMAGENT_RESTART_BUDGET:-3}"
BACKOFF="${IMAGENT_RESTART_BACKOFF:-5}"
RETRYABLE="${IMAGENT_RETRYABLE_CODES:-75 86 87 88}"

attempt=0
while :; do
  if [ "${attempt}" -eq 0 ]; then
    "$@"
  else
    # Later occurrences override: --resume is additive and idempotent.
    "$@" --resume
  fi
  rc=$?
  [ "${rc}" -eq 0 ] && exit 0

  retry=0
  for code in ${RETRYABLE}; do
    [ "${rc}" -eq "${code}" ] && retry=1
  done
  if [ "${retry}" -ne 1 ]; then
    echo "requeue: exit ${rc} is not retryable; giving up" >&2
    exit "${rc}"
  fi
  if [ "${attempt}" -ge "${BUDGET}" ]; then
    echo "requeue: restart budget (${BUDGET}) exhausted after exit ${rc}" >&2
    exit "${rc}"
  fi
  attempt=$((attempt + 1))
  delay=$((BACKOFF * (1 << (attempt - 1))))
  [ "${delay}" -gt 300 ] && delay=300
  echo "requeue: retryable exit ${rc}; restart ${attempt}/${BUDGET} with --resume in ${delay}s" >&2
  sleep "${delay}"
done
