#!/bin/bash
# Requeue wrapper: run the training command; when it dies with a
# RETRYABLE exit code (resilience/exitcodes.py — preemption 75,
# watchdog hard-exit 86, deadman peer-death 87, storage outage 88,
# elastic pod-resize 89, elastic exclusion 90), restart it with
# --resume after an exponential backoff, bounded by a restart budget.
# Non-retryable codes (config errors, reproducible faults) and an
# exhausted budget exit immediately with the original code, so a
# broken invocation never crash-loops.
#
# The restart budget is PER INCIDENT STREAK, not per run (mirroring
# the engine's rollback give-up semantics): an attempt that made clean
# progress — a newly COMPLETED epoch, read from the resume meta's
# "epoch" field (<ckpt-dir>/last_meta.json) — resets the consumed
# budget, so three isolated recoveries across a long run don't kill a
# healthy job on the fourth.
#
# Used as the per-task command under both launchers (slurm_tpu.sh's
# srun line, tpu_pod.sh's worker fan-out): every host of a degraded
# pod exits retryable within seconds of a peer death (the deadman
# makes the failure pod-wide and fast), so all tasks fall into this
# loop together, back off, and re-rendezvous onto --resume — the
# whole-pod requeue without scheduler support. With --elastic the
# relaunch re-forms whatever roster shows up (shrink or grow).
#
# Usage: requeue.sh <command...>
# Env knobs:
#   IMAGENT_RESTART_BUDGET   max restarts per no-progress streak
#                            (default 3)
#   IMAGENT_RESTART_BACKOFF  base backoff seconds, doubling per
#                            restart, capped at 300 (default 5)
#   IMAGENT_CKPT_DIR         where to read last_meta.json for the
#                            progress reset (default: the --ckpt-dir
#                            argument in the command, else
#                            "checkpoints")
#   IMAGENT_RETRYABLE_CODES  space-separated override of the retryable
#                            set. The default below is a literal (this
#                            script must work when Python cannot even
#                            start) and is pinned against
#                            resilience/exitcodes.retryable_codes() by
#                            tests/test_launch.py.
set -u

BUDGET="${IMAGENT_RESTART_BUDGET:-3}"
BACKOFF="${IMAGENT_RESTART_BACKOFF:-5}"
RETRYABLE="${IMAGENT_RETRYABLE_CODES:-75 86 87 88 89 90}"

# Resolve the checkpoint dir for the progress probe: explicit env, else
# the command's own --ckpt-dir (last occurrence wins, both = and
# space-separated forms), else the config default.
ckpt_dir="${IMAGENT_CKPT_DIR:-}"
if [ -z "${ckpt_dir}" ]; then
  ckpt_dir="checkpoints"
  prev=""
  for arg in "$@"; do
    case "${arg}" in
      --ckpt-dir=*) ckpt_dir="${arg#--ckpt-dir=}" ;;
    esac
    [ "${prev}" = "--ckpt-dir" ] && ckpt_dir="${arg}"
    prev="${arg}"
  done
fi

progress_epoch() {
  # The "epoch" field of the resume meta sidecar, no Python required.
  # Missing/unreadable/torn file prints nothing; callers default.
  sed -n 's/.*"epoch"[[:space:]]*:[[:space:]]*\(-\{0,1\}[0-9][0-9]*\).*/\1/p' \
    "${ckpt_dir}/last_meta.json" 2>/dev/null | head -n 1
}

last_epoch="$(progress_epoch)"
last_epoch="${last_epoch:--1000}"

attempt=0
while :; do
  if [ "${attempt}" -eq 0 ]; then
    "$@"
  else
    # Later occurrences override: --resume is additive and idempotent.
    "$@" --resume
  fi
  rc=$?
  [ "${rc}" -eq 0 ] && exit 0

  retry=0
  for code in ${RETRYABLE}; do
    [ "${rc}" -eq "${code}" ] && retry=1
  done
  if [ "${retry}" -ne 1 ]; then
    echo "requeue: exit ${rc} is not retryable; giving up" >&2
    exit "${rc}"
  fi
  cur_epoch="$(progress_epoch)"
  cur_epoch="${cur_epoch:--1000}"
  if [ "${cur_epoch}" -gt "${last_epoch}" ]; then
    # Clean progress since the last probe: a newly completed epoch in
    # the resume meta. The incident streak is over — reset the budget.
    if [ "${attempt}" -gt 0 ]; then
      echo "requeue: clean progress (epoch $((cur_epoch + 1)) complete per resume meta); restart budget reset" >&2
    fi
    attempt=0
  fi
  last_epoch="${cur_epoch}"
  if [ "${attempt}" -ge "${BUDGET}" ]; then
    echo "requeue: restart budget (${BUDGET}) exhausted after exit ${rc}" >&2
    exit "${rc}"
  fi
  attempt=$((attempt + 1))
  delay=$((BACKOFF * (1 << (attempt - 1))))
  [ "${delay}" -gt 300 ] && delay=300
  echo "requeue: retryable exit ${rc}; restart ${attempt}/${BUDGET} with --resume in ${delay}s" >&2
  sleep "${delay}"
done
