#!/bin/bash
# Direct TPU-pod launcher (no Slurm): run the same command on every
# worker of a Cloud TPU pod slice. On TPU VMs, JAX discovers the pod
# topology from the runtime — no coordinator flags needed
# (jax.distributed.initialize() is auto-configured by the TPU metadata).
#
# Usage:
#   bash tpu_pod.sh <tpu-name> <zone> [training flags...]
#
# This is the operator-ergonomics equivalent of "one sbatch, N ranks"
# (imagenet.sh:26) for pods: one command fans out to all workers.

set -euo pipefail
TPU_NAME="$1"; shift
ZONE="$1"; shift

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" --worker=all \
  --command "cd ~/imagent_tpu && python -m imagent_tpu --backend=tpu $*"
