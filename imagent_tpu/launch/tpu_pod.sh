#!/bin/bash
# Direct TPU-pod launcher (no Slurm): run the same command on every
# worker of a Cloud TPU pod slice. On TPU VMs, JAX discovers the pod
# topology from the runtime — no coordinator flags needed
# (jax.distributed.initialize() is auto-configured by the TPU metadata).
#
# Usage:
#   bash tpu_pod.sh <tpu-name> <zone> [training flags...]
#
# This is the operator-ergonomics equivalent of "one sbatch, N ranks"
# (imagenet.sh:26) for pods: one command fans out to all workers.

set -euo pipefail
TPU_NAME="$1"; shift
ZONE="$1"; shift

# Each worker runs under the requeue wrapper: retryable exits
# (preemption, watchdog hard-exit, deadman peer-death, storage outage —
# resilience/exitcodes.py) restart that worker's command with --resume
# after a backoff; the deadman (--peer-deadline-secs) makes any
# partial-pod failure fail fast on every survivor so the whole pod
# re-rendezvouses together.
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" --worker=all \
  --command "cd ~/imagent_tpu && bash imagent_tpu/launch/requeue.sh python -m imagent_tpu --backend=tpu --peer-deadline-secs=60 $*"
