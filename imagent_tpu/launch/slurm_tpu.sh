#!/bin/bash
# Slurm launcher for TPU-VM clusters — the reference's imagenet.sh
# (imagenet.sh:1-27) re-done for TPU pods.
#
# Differences from the reference (by design, not omission):
#  * ONE task per host (JAX wants one process per TPU VM worker; the
#    reference ran one per GPU, imagenet.sh:8-9).
#  * NO NCCL env block — the reference's transport tuning
#    (NCCL_P2P_DISABLE/NCCL_LL_THRESHOLD/NCCL_SOCKET_IFNAME/NCCL_IB_*,
#    imagenet.sh:19-23) has no TPU analogue: XLA compiles collectives
#    onto ICI and needs no per-job transport vars (SURVEY §5).
#  * Rendezvous: imagent_tpu.cluster parses the same SLURM_* vars the
#    reference did (imagenet.py:225-238) and feeds
#    jax.distributed.initialize() instead of exporting MASTER_ADDR/PORT.
#
#SBATCH --job-name=imagent_tpu
#SBATCH --partition=tpu
#SBATCH --exclusive
#SBATCH --nodes=8
#SBATCH --ntasks=8
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task=96
#SBATCH --hint=nomultithread
#SBATCH --time=24:00:00
#SBATCH --output=imagent_tpu_%j.out
#SBATCH --error=imagent_tpu_%j.err

cd "${SLURM_SUBMIT_DIR}"

# Per-task requeue wrapper (launch/requeue.sh): a task exiting with a
# retryable code — preemption 75, watchdog hard-exit 86, deadman
# peer-death 87, storage outage 88 (resilience/exitcodes.py) — is
# restarted with --resume after a backoff, bounded by
# IMAGENT_RESTART_BUDGET. The deadman (--peer-deadline-secs) makes a
# partial-pod failure fail FAST on every survivor, so all tasks drop
# into the wrapper together and re-rendezvous onto the last good
# checkpoint — no walltime burned in a half-dead allreduce.
srun bash imagent_tpu/launch/requeue.sh python -m imagent_tpu \
  --backend=tpu \
  --arch=resnet50 \
  --batch-size=128 \
  --epochs=90 \
  --lr=0.1 \
  --data-root=/data/imagenet \
  --peer-deadline-secs=60 \
  --save-model "$@"
