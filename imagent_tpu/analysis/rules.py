"""jaxlint rules: JAX/TPU-aware static checks over module ASTs.

Each rule targets a defect class that is cheap to catch at review time
and expensive to catch on a pod: a host sync buried in a jitted step
serializes every device behind a Python round-trip; a reused PRNG key
silently correlates augmentations; a Python branch on a traced value
either crashes at trace time or triggers a recompile storm; iterating a
``set`` while building a pytree gives different flattening orders on
different hosts (different collective layouts → hang or silent
corruption); a train step jitted without donation doubles the
parameter+optimizer HBM footprint; an implicit-dtype array on the wire
path quietly re-inflates the uint8 wire format to float64; a benchmark
that stops its timer without a device sync measures dispatch, not work;
a TensorBoard tag interpolating a step number mints a fresh series
every step until the dashboard (and the event file) drowns; a blocking
device→host fetch on an in-flight result inside the prefetched step
loop re-introduces the per-step sync the async dispatch pipeline
exists to avoid.

Detection is intra-module and intentionally conservative: a rule fires
only on patterns it can see whole (see docs/STATIC_ANALYSIS.md for the
known blind spots).  False positives are silenced per line with
``# jaxlint: disable=<rule>`` or grandfathered in
``analysis/baseline.json`` — both require a justification.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Iterator

# --------------------------------------------------------------------------
# Findings and the rule registry
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit, anchored to a source line.

    ``code`` is the stripped source line — the baseline fingerprint, so
    grandfathered entries survive unrelated line-number drift."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    code: str = ""
    end_line: int = 0  # statement extent: suppressions anywhere on
    # [line, end_line] apply (multiline calls put the comment last)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    check: Callable[["ModuleContext"], Iterator[Finding]]


RULES: dict[str, Rule] = {}


def rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = Rule(name, doc, fn)
        return fn
    return deco


# --------------------------------------------------------------------------
# Shared AST machinery
# --------------------------------------------------------------------------


def _import_aliases(tree: ast.AST) -> dict[str, str]:
    """Local name → canonical dotted prefix, from the module's imports.

    ``import jax.numpy as jnp`` → ``jnp: jax.numpy``; ``from jax import
    random`` → ``random: jax.random``; ``import numpy as np`` →
    ``np: numpy``.  Unaliased ``import a.b`` binds only ``a``."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    aliases[root] = root
        elif isinstance(node, ast.ImportFrom) and node.module \
                and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _qualname(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Canonical dotted name of a Name/Attribute chain, or None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def _iter_defs(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_body_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body EXCLUDING nested function/lambda bodies —
    the per-scope view the key-reuse and timer counting need."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _param_names(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


_JIT_WRAPPERS = ("jax.jit", "jax.pmap")


def _is_jit_wrapper(qual: str | None) -> bool:
    return qual is not None and (
        qual in _JIT_WRAPPERS or qual.endswith(".shard_map")
        or qual == "shard_map")


def _wrapped_fn_name(call: ast.Call,
                     aliases: dict[str, str]) -> str | None:
    """The local function name a jit/shard_map/pmap call wraps, seeing
    through one ``functools.partial`` layer."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Call) and _qualname(
            target.func, aliases) == "functools.partial" and target.args:
        target = target.args[0]
    if isinstance(target, ast.Name):
        return target.id
    return None


def _static_param_names(call: ast.Call,
                        fn: ast.FunctionDef) -> set[str]:
    """Parameter names a jit call marks static (static_argnames /
    static_argnums) — those arrive as Python values, not tracers, so
    host coercion and branching on them are sound."""
    names: set[str] = set()
    positional = [p.arg for p in (*fn.args.posonlyargs, *fn.args.args)]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and \
                        isinstance(c.value, str):
                    names.add(c.value)
        elif kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and \
                        isinstance(c.value, int) and \
                        0 <= c.value < len(positional):
                    names.add(positional[c.value])
    return names


def _find_jit_bodies(
        tree: ast.AST, aliases: dict[str, str]
) -> list[tuple[ast.FunctionDef, set[str]]]:
    """(FunctionDef, static param names) pairs for bodies that trace
    under jit/pmap/shard_map.

    Marked when (a) decorated with ``jax.jit``/``jax.pmap`` (directly or
    via ``partial``), or (b) the def's name is passed to a
    jit/pmap/shard_map call anywhere in the module.  Name-based, so a
    function reassigned between definition and the jit call can be
    missed — acceptable for this codebase's builder idiom."""
    by_name: dict[str, list[ast.FunctionDef]] = {}
    marked: dict[int, tuple[ast.FunctionDef, set[str]]] = {}

    def mark(fn: ast.FunctionDef, static: set[str]) -> None:
        prev = marked.get(id(fn))
        if prev is None:
            marked[id(fn)] = (fn, set(static))
        else:
            prev[1].update(static)

    for fn in _iter_defs(tree):
        by_name.setdefault(fn.name, []).append(fn)
        for dec in fn.decorator_list:
            if _is_jit_wrapper(_qualname(dec, aliases)):
                mark(fn, set())
            elif isinstance(dec, ast.Call):
                dq = _qualname(dec.func, aliases)
                if _is_jit_wrapper(dq):
                    mark(fn, _static_param_names(dec, fn))
                elif dq == "functools.partial" and dec.args and \
                        _is_jit_wrapper(_qualname(dec.args[0], aliases)):
                    mark(fn, _static_param_names(dec, fn))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _is_jit_wrapper(_qualname(node.func, aliases)):
            name = _wrapped_fn_name(node, aliases)
            for fn in by_name.get(name, ()):
                mark(fn, _static_param_names(node, fn))
    return list(marked.values())


class ModuleContext:
    """Everything the rules need about one parsed module."""

    def __init__(self, rel_path: str, source: str, tree: ast.Module):
        self.rel_path = rel_path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.aliases = _import_aliases(tree)
        self.jit_bodies = _find_jit_bodies(tree, self.aliases)

    def qual(self, node: ast.AST) -> str | None:
        return _qualname(node, self.aliases)

    def scopes(self) -> Iterator[ast.AST]:
        """The module plus every function def — one per analysis scope."""
        yield self.tree
        yield from _iter_defs(self.tree)

    def finding(self, node: ast.AST, rule_name: str,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        code = self.lines[line - 1].strip() if line <= len(self.lines) \
            else ""
        return Finding(self.rel_path, line, col, rule_name, message,
                       code, getattr(node, "end_lineno", None) or line)


# --------------------------------------------------------------------------
# Rule 1: host-sync-in-jit
# --------------------------------------------------------------------------

_HOST_FETCH_CALLS = {"numpy.asarray", "numpy.array"}
_HOST_FETCH_METHODS = {"item", "tolist"}
_TRACER_COERCIONS = {"float", "int", "bool"}


def _rooted_at_param(node: ast.AST, params: set[str]) -> bool:
    """Whether an expression chains straight off a traced parameter
    (tracer → host coercion).  Chains that pass through ``.shape`` are
    static Python ints under jit and stay legal."""
    while True:
        if isinstance(node, ast.Attribute):
            if node.attr == "shape":
                return False
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            break
    return isinstance(node, ast.Name) and node.id in params


@rule("host-sync-in-jit",
      "device→host fetch inside a jitted/shard_mapped body breaks "
      "tracing or forces a per-step sync")
def check_host_sync(ctx: ModuleContext) -> Iterator[Finding]:
    for fn, static in ctx.jit_bodies:
        params = _param_names(fn) - static
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qual = ctx.qual(node.func)
            if qual in _HOST_FETCH_CALLS:
                yield ctx.finding(
                    node, "host-sync-in-jit",
                    f"{qual}() inside jitted `{fn.name}` materializes a "
                    "tracer on host; keep the value in jnp or move the "
                    "fetch outside the compiled step")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_FETCH_METHODS:
                yield ctx.finding(
                    node, "host-sync-in-jit",
                    f".{node.func.attr}() inside jitted `{fn.name}` is a "
                    "device→host sync; under trace it fails, under "
                    "callback it serializes the step")
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in _TRACER_COERCIONS and \
                    node.func.id not in ctx.aliases and node.args and \
                    _rooted_at_param(node.args[0], params):
                yield ctx.finding(
                    node, "host-sync-in-jit",
                    f"{node.func.id}() applied to traced argument of "
                    f"`{fn.name}` — a concretization error at trace "
                    "time; use jnp ops on the tracer instead")


# --------------------------------------------------------------------------
# Rule 2: prng-key-reuse
# --------------------------------------------------------------------------

# jax.random.* that make or derive keys rather than consume entropy.
# Deriving several children from one parent via distinct fold_in data
# (train.py's idiom) is sound; two *draws* from one key are correlated.
_KEY_MAKERS = {"key", "PRNGKey", "split", "fold_in", "clone", "key_data",
               "wrap_key_data", "key_impl", "default_prng_impl"}


def _assigned_names(node: ast.AST) -> Iterator[tuple[ast.AST, str]]:
    targets: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    elif isinstance(node, ast.For):
        targets = [node.target]
    elif isinstance(node, ast.NamedExpr):
        targets = [node.target]
    elif isinstance(node, ast.withitem) and node.optional_vars:
        targets = [node.optional_vars]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                yield t, sub.id


def _is_key_draw(node: ast.AST, ctx: ModuleContext) -> str | None:
    """The key variable name a ``jax.random.*`` draw consumes, if any."""
    if isinstance(node, ast.Call) and node.args and \
            isinstance(node.args[0], ast.Name):
        qual = ctx.qual(node.func)
        if qual and qual.startswith("jax.random.") and \
                qual.rsplit(".", 1)[1] not in _KEY_MAKERS:
            return node.args[0].id
    return None


@rule("prng-key-reuse",
      "drawing twice from one PRNG key correlates the draws — split or "
      "fold_in between uses")
def check_key_reuse(ctx: ModuleContext) -> Iterator[Finding]:
    """Branch-aware linear scan: mutually exclusive ``if``/``else``
    (and ternary) arms, and ``try`` vs its ``except`` handlers, each
    see a copy of the per-key draw counts and merge as the per-name
    max afterwards — one draw per arm is NOT reuse, a draw before the
    branch plus one inside (or one after) is.  Loop bodies are scanned
    twice, so a draw from a loop-invariant key (identical values every
    iteration — the correlated-inits classic) fires; a key rebound
    inside the body stays clean.  Rebinding (``split``/``fold_in``
    assignment) resets the count."""
    findings: list[Finding] = []

    def merge_max(counts: dict[str, int], *states: dict) -> None:
        for st in states:
            for name in st:
                counts[name] = max(counts.get(name, 0), st[name])

    def visit(node: ast.AST, counts: dict[str, int]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # separate scope
        if isinstance(node, (ast.If, ast.IfExp)):
            visit(node.test, counts)
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            orelse = node.orelse if isinstance(node.orelse, list) \
                else [node.orelse]
            after_body = dict(counts)
            after_else = dict(counts)
            for n in body:
                visit(n, after_body)
            for n in orelse:
                visit(n, after_else)
            counts.clear()
            merge_max(counts, after_body, after_else)
            return
        if isinstance(node, ast.Try):
            # A handler is an alternative path to the draw that raised:
            # try-draw + except-fallback-draw is one draw per run.
            pre = dict(counts)
            for n in (*node.body, *node.orelse):
                visit(n, counts)
            handler_states = []
            for h in node.handlers:
                hc = dict(pre)
                for n in h.body:
                    visit(n, hc)
                handler_states.append(hc)
            merge_max(counts, *handler_states)
            for n in node.finalbody:
                visit(n, counts)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.NamedExpr)):
            if getattr(node, "value", None) is not None:
                visit(node.value, counts)
            for _t, name in _assigned_names(node):
                counts[name] = 0  # fresh binding
            return
        if isinstance(node, (ast.For, ast.While)):
            # Two passes over the body: a key consumed every iteration
            # without an in-body rebind reaches count 2 on the second
            # pass (the per-iteration reuse a single pass cannot see).
            if isinstance(node, ast.For):
                visit(node.iter, counts)
            else:
                visit(node.test, counts)
            for _pass in range(2):
                if isinstance(node, ast.For):
                    for _t, name in _assigned_names(node):
                        counts[name] = 0  # loop target: fresh each iter
                for n in node.body:
                    visit(n, counts)
            for n in node.orelse:
                visit(n, counts)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, counts)
        name = _is_key_draw(node, ctx)
        if name is not None:
            counts[name] = counts.get(name, 0) + 1
            if counts[name] == 2:
                findings.append(ctx.finding(
                    node, "prng-key-reuse",
                    f"key `{name}` already consumed by an earlier "
                    "jax.random draw on this path; split/fold_in "
                    "before drawing again (reused keys correlate "
                    "augmentations/inits silently)"))

    for scope in ctx.scopes():
        counts: dict[str, int] = {}
        body = scope.body if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)) \
            else []
        for stmt in body:
            visit(stmt, counts)
    # The second loop-body pass can rediscover an in-body reuse at the
    # same node — report each site once.
    seen: set[tuple[int, int]] = set()
    for f_ in findings:
        if (f_.line, f_.col) not in seen:
            seen.add((f_.line, f_.col))
            yield f_


def _top_scope_walk(tree: ast.AST) -> Iterator[ast.AST]:
    """Module-level statements, excluding function/class bodies."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
# Rule 3: recompile-hazard
# --------------------------------------------------------------------------


def _names_outside_is_compare(test: ast.AST) -> Iterator[ast.Name]:
    """Name nodes in a test expression, skipping operands of pure
    ``is``/``is not`` comparisons (None-structure checks are static
    under jit and a legitimate branch)."""
    skip: set[int] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for sub in ast.walk(node):
                skip.add(id(sub))
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and id(node) not in skip:
            yield node


@rule("recompile-hazard",
      "Python control flow / formatting on traced values inside a jit "
      "body — trace error or a recompile per distinct value")
def check_recompile_hazard(ctx: ModuleContext) -> Iterator[Finding]:
    for fn, static in ctx.jit_bodies:
        params = _param_names(fn) - static
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hit = next(
                    (n for n in _names_outside_is_compare(node.test)
                     if n.id in params), None)
                if hit is not None:
                    kind = "while" if isinstance(node, ast.While) \
                        else "if"
                    yield ctx.finding(
                        node, "recompile-hazard",
                        f"Python `{kind}` on traced argument "
                        f"`{hit.id}` of `{fn.name}`: branch with "
                        "lax.cond/jnp.where, or hoist the decision to "
                        "the builder")
                elif any(isinstance(n, ast.Attribute)
                         and n.attr == "shape"
                         for n in ast.walk(node.test)):
                    yield ctx.finding(
                        node, "recompile-hazard",
                        f"branching on `.shape` inside `{fn.name}` "
                        "specializes the compile per input geometry — "
                        "one recompile per distinct shape reaching "
                        "this step")
            elif isinstance(node, ast.JoinedStr):
                for fv in node.values:
                    if isinstance(fv, ast.FormattedValue) and any(
                            isinstance(n, ast.Name) and n.id in params
                            for n in ast.walk(fv.value)):
                        yield ctx.finding(
                            node, "recompile-hazard",
                            f"f-string formats traced argument inside "
                            f"`{fn.name}` — str(tracer) escapes the "
                            "trace (use jax.debug.print)")
                        break


# --------------------------------------------------------------------------
# Rule 4: nondeterministic-pytree-order
# --------------------------------------------------------------------------

_SET_METHODS = {"intersection", "union", "difference",
                "symmetric_difference"}
_SET_OPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)


def _is_set_expr(node: ast.AST, ctx: ModuleContext,
                 set_vars: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in set_vars
    if isinstance(node, ast.Call):
        qual = ctx.qual(node.func)
        if qual in ("set", "frozenset"):
            return True
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SET_METHODS:
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, _SET_OPS):
        return _is_set_expr(node.left, ctx, set_vars) or \
            _is_set_expr(node.right, ctx, set_vars)
    return False


@rule("nondeterministic-pytree-order",
      "iterating a set while building a pytree/param dict gives "
      "per-host orders — divergent collective layouts at scale")
def check_set_iteration(ctx: ModuleContext) -> Iterator[Finding]:
    # Source-ordered scan per scope: an assignment updates which names
    # hold sets AT THAT POINT, so `s = set(x); s = sorted(s); for v in
    # s` is clean (the rebinding de-sets `s`) and iterating before the
    # set assignment never flags.
    for scope in ctx.scopes():
        walk_fn = (_own_body_walk if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef))
            else _top_scope_walk)
        events: list[tuple[int, int, str, ast.AST]] = []
        for node in walk_fn(scope):
            if isinstance(node, ast.Assign):
                events.append((node.lineno, node.col_offset,
                               "assign", node))
            elif isinstance(node, ast.For):
                events.append((node.iter.lineno, node.iter.col_offset,
                               "iter", node.iter))
                # The loop variable itself is an item, not a set.
                events.append((node.iter.lineno, node.iter.col_offset,
                               "unset", node))
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                events.extend((g.iter.lineno, g.iter.col_offset,
                               "iter", g.iter)
                              for g in node.generators)
        events.sort(key=lambda e: (e[0], e[1]))
        set_vars: set[str] = set()
        for _ln, _col, kind, node in events:
            if kind == "assign":
                names = {name for _t, name in _assigned_names(node)}
                if _is_set_expr(node.value, ctx, set_vars):
                    set_vars |= names
                else:
                    set_vars -= names  # rebound to a non-set
            elif kind == "unset":
                set_vars -= {name for _t, name
                             in _assigned_names(node)}
            else:
                if isinstance(node, ast.Call) and \
                        ctx.qual(node.func) == "sorted":
                    continue  # sorted() fixes the order
                if _is_set_expr(node, ctx, set_vars):
                    yield ctx.finding(
                        node, "nondeterministic-pytree-order",
                        "iteration over a set: hash order is "
                        "per-process, so pytrees/param dicts built "
                        "from it flatten differently across hosts "
                        "(mismatched collectives hang the pod) — wrap "
                        "in sorted()")


# --------------------------------------------------------------------------
# Rule 5: missing-donation
# --------------------------------------------------------------------------


def _is_train_step_builder(name: str) -> bool:
    return "train_step" in name or (
        name.startswith("make_") and "step" in name
        and "eval" not in name)


@rule("missing-donation",
      "jitting a train step without donate_argnums doubles the "
      "params+optimizer HBM footprint")
def check_missing_donation(ctx: ModuleContext) -> Iterator[Finding]:
    for fn in _iter_defs(ctx.tree):
        if not _is_train_step_builder(fn.name):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    ctx.qual(node.func) == "jax.jit" and not any(
                        kw.arg in ("donate_argnums", "donate_argnames")
                        for kw in node.keywords):
                yield ctx.finding(
                    node, "missing-donation",
                    f"jax.jit in train-step builder `{fn.name}` "
                    "without donate_argnums/donate_argnames: the old "
                    "TrainState stays live across the update — "
                    "2x params+opt memory, the difference between "
                    "fitting and OOM at scale")


# --------------------------------------------------------------------------
# Rule 6: dtype-contract
# --------------------------------------------------------------------------

# Creators whose dtype defaults (float64/int64) silently re-inflate the
# uint8 wire format; positional index at which dtype may appear.
_CREATOR_DTYPE_POS = {
    "numpy.zeros": 1, "numpy.ones": 1, "numpy.empty": 1,
    "numpy.full": 2, "numpy.asarray": 1, "numpy.array": 1,
    "jax.numpy.zeros": 1, "jax.numpy.ones": 1, "jax.numpy.empty": 1,
    "jax.numpy.full": 2, "jax.numpy.asarray": 1, "jax.numpy.array": 1,
}
_WIDE_CASTS = {"float64", "double"}


def _in_wire_scope(ctx: ModuleContext) -> bool:
    parts = ctx.rel_path.replace("\\", "/").split("/")
    return "data" in parts[:-1]


@rule("dtype-contract",
      "implicit array dtype on the wire-format path re-inflates the "
      "uint8 wire to float64 silently")
def check_dtype_contract(ctx: ModuleContext) -> Iterator[Finding]:
    scopes: list[ast.AST] = []
    if _in_wire_scope(ctx):
        scopes.append(ctx.tree)
    else:
        scopes.extend(fn for fn in _iter_defs(ctx.tree)
                      if fn.name == "make_input_prep")
    for scope in scopes:
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            qual = ctx.qual(node.func)
            pos = _CREATOR_DTYPE_POS.get(qual or "")
            if pos is not None:
                has_dtype = len(node.args) > pos or any(
                    kw.arg == "dtype" for kw in node.keywords)
                if not has_dtype:
                    yield ctx.finding(
                        node, "dtype-contract",
                        f"{qual}() without an explicit dtype on the "
                        "wire-format path: the float64/int64 default "
                        "breaks the raw-uint8 wire contract "
                        "(data/pipeline.py::Batch) and inflates "
                        "IPC/H2D bytes 8x")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and node.args:
                arg = node.args[0]
                tq = ctx.qual(arg) or ""
                lit = arg.value if isinstance(arg, ast.Constant) else ""
                if tq.rsplit(".", 1)[-1] in _WIDE_CASTS or \
                        lit in _WIDE_CASTS:
                    yield ctx.finding(
                        node, "dtype-contract",
                        "float64 cast on the wire-format path: 8 "
                        "bytes/value over IPC and H2D where the "
                        "contract is 1 (uint8)")


# --------------------------------------------------------------------------
# Rule 7: telemetry-tag-format
# --------------------------------------------------------------------------

_TB_WRITE_METHODS = {"add_scalar", "add_scalars", "add_histogram"}
# namespace/snake_case: lowercase segments separated by "/", each
# starting with a letter — what every telemetry series in the repo
# uses ("goodput/fraction", "steptime/p95_ms", "data/h2d_mb").
_TAG_RE = re.compile(r"^[a-z][a-z0-9_]*(/[a-z][a-z0-9_]*)*$")
# OpenMetrics family names (telemetry/export.py Exposition.family):
# strict snake_case, no slashes/colons. A call site is judged as a
# family declaration when its second argument is a literal metric
# type — the Exposition signature — so unrelated `.family(...)`
# methods elsewhere are never misjudged.
_OM_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_OM_TYPES = {"gauge", "counter", "info", "histogram", "summary"}


@rule("telemetry-tag-format",
      "TB tags and exporter metric families must be snake_case "
      "literals; interpolating values (step numbers) into a name "
      "mints unbounded series")
def check_telemetry_tags(ctx: ModuleContext) -> Iterator[Finding]:
    """Conservative: only literal and f-string first arguments to the
    writer methods are judged (a variable tag is invisible here — the
    call sites that build tags dynamically must keep the family
    bounded, which is what the suppression justification documents).
    Exporter family declarations (``.family(name, "gauge", ...)``) get
    the same treatment with the OpenMetrics name grammar: a scraper's
    series set must be bounded and greppable, so family names are
    literal snake_case, never f-string-minted."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.args):
            continue
        if (node.func.attr == "family" and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value in _OM_TYPES):
            name = node.args[0]
            if isinstance(name, ast.JoinedStr):
                if any(isinstance(v, ast.FormattedValue)
                       for v in name.values):
                    yield ctx.finding(
                        node, "telemetry-tag-format",
                        "f-string OpenMetrics family name in "
                        ".family(): every distinct interpolated value "
                        "mints a NEW metric family for the scraper — "
                        "put variables in LABELS (bounded), or "
                        "suppress with the justification that the "
                        "family set is bounded")
            elif isinstance(name, ast.Constant) \
                    and isinstance(name.value, str) \
                    and not _OM_NAME_RE.match(name.value):
                yield ctx.finding(
                    node, "telemetry-tag-format",
                    f"OpenMetrics family name {name.value!r} is not "
                    "snake_case (^[a-z][a-z0-9_]*$): scrapers and "
                    "recording rules expect the Prometheus naming "
                    "grammar (no slashes, no capitals)")
            continue
        if node.func.attr not in _TB_WRITE_METHODS:
            continue
        tag = node.args[0]
        if isinstance(tag, ast.JoinedStr):
            if any(isinstance(v, ast.FormattedValue)
                   for v in tag.values):
                yield ctx.finding(
                    node, "telemetry-tag-format",
                    f"f-string tag in {node.func.attr}(): every "
                    "distinct interpolated value mints a NEW "
                    "TensorBoard series (a step number in the tag = "
                    "one series per step) — put variables in the "
                    "step/value arguments, or suppress with the "
                    "justification that the family is bounded")
        elif isinstance(tag, ast.Constant) and isinstance(tag.value,
                                                          str):
            if not _TAG_RE.match(tag.value):
                yield ctx.finding(
                    node, "telemetry-tag-format",
                    f"tag {tag.value!r} is not namespace/snake_case "
                    "(^[a-z][a-z0-9_]*(/segment)*$): mixed-case and "
                    "ad-hoc tags scatter related series across the "
                    "TB sidebar instead of grouping under one "
                    "namespace")


# --------------------------------------------------------------------------
# Rule 8: untimed-block
# --------------------------------------------------------------------------

_TIMER_CALLS = {"time.time", "time.perf_counter", "time.monotonic"}
# np.asarray / device_get are accepted as syncs: on the experimental
# axon platform a hard D2H fetch is the only reliable barrier
# (block_until_ready returns early — bench.py), so the repo's
# benchmarks sync by fetching a reduction.
_SYNC_CALLS = {"jax.block_until_ready", "jax.device_get",
               "numpy.asarray", "numpy.array"}


def _in_bench_scope(ctx: ModuleContext) -> bool:
    parts = ctx.rel_path.replace("\\", "/").split("/")
    return "benchmarks" in parts[:-1] or \
        parts[-1].startswith("bench")


@rule("untimed-block",
      "timing device work without a sync measures async dispatch, not "
      "the computation")
def check_untimed_block(ctx: ModuleContext) -> Iterator[Finding]:
    if not _in_bench_scope(ctx):
        return
    if not any(a == "jax" or a.startswith("jax.")
               for a in ctx.aliases.values()):
        return  # no device work to mistime
    for scope in ctx.scopes():
        own = (_own_body_walk(scope) if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef))
            else _top_scope_walk(scope))
        timers = sorted(
            (n for n in own if isinstance(n, ast.Call)
             and ctx.qual(n.func) in _TIMER_CALLS),
            key=lambda n: (n.lineno, n.col_offset))
        if len(timers) < 2:
            continue
        # A sync counts only at/after the first timer: a warmup-only
        # sync BEFORE the timed region still leaves the measurement
        # bracketing nothing but async dispatch.
        start = (timers[0].lineno, timers[0].col_offset)
        synced = any(
            isinstance(n, ast.Call) and (
                ctx.qual(n.func) in _SYNC_CALLS
                or (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "block_until_ready"))
            and (n.lineno, n.col_offset) > start
            for n in ast.walk(scope))
        if not synced:
            name = getattr(scope, "name", "<module>")
            yield ctx.finding(
                timers[1], "untimed-block",
                f"`{name}` brackets work with timers but never syncs "
                "the device (block_until_ready / device_get / hard "
                "np.asarray fetch): jax dispatch is async, so the "
                "measured time is queueing, not compute")


# --------------------------------------------------------------------------
# Rule 9: blocking-call-in-step-loop
# --------------------------------------------------------------------------

# The prefetched step loop's invariant (engine.py): the loop body
# dispatches asynchronously and NOTHING in it blocks on an in-flight
# step result — metrics are consumed by a frontier lagged _GUARD_LAG
# steps behind the dispatch (already retired → the fetch is free).
_STEP_LOOP_SOURCES = {"device_prefetch", "Prefetcher"}
_BLOCKING_FETCH_CALLS = {"numpy.asarray", "numpy.array",
                         "jax.device_get", "jax.block_until_ready"}
_BLOCKING_FETCH_METHODS = {"item", "tolist", "block_until_ready",
                           # Chip-accountant APIs (ISSUE 19): compile
                           # analyses and allocator stats are host
                           # syncs too — capture belongs at step-build
                           # time (telemetry/chipacct.py), never in
                           # the step loop.
                           "memory_stats", "cost_analysis",
                           "memory_analysis"}
_LAG_SENTINEL = "_GUARD_LAG"


def _has_step_source_call(node: ast.AST, ctx: ModuleContext,
                          loop_vars: set[str]) -> bool:
    """Whether an expression contains a device_prefetch/Prefetcher call
    or references a name bound from one."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            qual = ctx.qual(sub.func) or ""
            if qual.rsplit(".", 1)[-1] in _STEP_LOOP_SOURCES:
                return True
        elif isinstance(sub, ast.Name) and sub.id in loop_vars:
            return True
    return False


@rule("blocking-call-in-step-loop",
      "blocking device→host fetch on an in-flight step result inside a "
      "prefetched step loop — re-introduces the per-step sync; read "
      "from the _GUARD_LAG-lagged frontier instead")
def check_blocking_in_step_loop(ctx: ModuleContext) -> Iterator[Finding]:
    """Fires on ``np.asarray``/``np.array``/``jax.device_get``/
    ``jax.block_until_ready`` calls and ``.item()``/``.tolist()``/
    ``.block_until_ready()`` — plus the chip-accountant surfaces
    ``.memory_stats()``/``.cost_analysis()``/``.memory_analysis()``
    (startup-capture-only APIs) — methods inside the body of a ``for`` loop
    that iterates ``device_prefetch(...)``/``Prefetcher(...)`` (or a
    name assigned from one, tracked in source order) — the engine's
    step loops.  Exemption: a statement whose subtree references
    ``_GUARD_LAG`` reads the lagged frontier — that step has already
    retired, so the fetch is a free D2H, not a drain.  Blind spot
    (documented in docs/STATIC_ANALYSIS.md): a prefetcher that reaches
    the loop only as a function parameter is invisible; keep the
    engine's builder idiom (assign from the constructor expression)."""
    for scope in ctx.scopes():
        walk_fn = (_own_body_walk if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef))
            else _top_scope_walk)
        nodes = sorted(
            (n for n in walk_fn(scope)
             if isinstance(n, (ast.Assign, ast.NamedExpr, ast.For))),
            key=lambda n: (n.lineno, n.col_offset))
        loop_vars: set[str] = set()
        step_loops: list[ast.For] = []
        for node in nodes:
            if isinstance(node, (ast.Assign, ast.NamedExpr)):
                names = {name for _t, name in _assigned_names(node)}
                if _has_step_source_call(node.value, ctx, loop_vars):
                    loop_vars |= names
                else:
                    loop_vars -= names  # rebound to something else
            elif _has_step_source_call(node.iter, ctx, loop_vars):
                step_loops.append(node)
        for loop in step_loops:
            for stmt in loop.body:
                lagged = any(isinstance(n, ast.Name)
                             and n.id == _LAG_SENTINEL
                             for n in ast.walk(stmt))
                if lagged:
                    continue
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.Lambda)):
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    qual = ctx.qual(node.func)
                    if qual in _BLOCKING_FETCH_CALLS:
                        yield ctx.finding(
                            node, "blocking-call-in-step-loop",
                            f"{qual}() inside the prefetched step loop "
                            "blocks on an in-flight step result — the "
                            "per-step sync the async dispatch pipeline "
                            "exists to avoid; consume from a frontier "
                            f"lagged {_LAG_SENTINEL} steps behind the "
                            "dispatch (engine._LaggedMetrics), or "
                            "suppress with justification")
                    elif isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _BLOCKING_FETCH_METHODS:
                        yield ctx.finding(
                            node, "blocking-call-in-step-loop",
                            f".{node.func.attr}() inside the prefetched "
                            "step loop is a device→host sync on an "
                            "in-flight result — it drains the dispatch "
                            "pipeline every step; read the "
                            f"{_LAG_SENTINEL}-lagged frontier instead, "
                            "or suppress with justification")
