"""jaxlint runner: file discovery, suppressions, baseline, orchestration.

Suppression syntax (same line as the finding)::

    x = np.asarray(y)  # jaxlint: disable=host-sync-in-jit -- <why>

``disable=all`` silences every rule on that line.  The ``-- <why>``
justification is required: a suppression without one is itself reported
(``bare-suppression``), so silenced findings stay auditable.

Baseline (``analysis/baseline.json``): a JSON list of
``{"path", "rule", "code", "reason"}`` entries for grandfathered
findings — matched by (path, rule, stripped source line), so entries
survive line drift.  Every entry must carry a non-empty ``reason``.
Entries that no longer match anything are reported as stale (the fix
landed: delete the entry) without failing the run.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Iterable, Iterator

from imagent_tpu.analysis.graph import ProjectGraph
from imagent_tpu.analysis.podrules import (DEFAULT_MANIFEST,
                                           PROJECT_RULES, PodlintConfig,
                                           run_project_rules)
from imagent_tpu.analysis.rules import RULES, Finding, ModuleContext

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(.*?))?\s*$")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]          # actionable (unsuppressed) hits
    suppressed: int
    baselined: int
    stale_baseline: list[dict]
    files_checked: int
    # Suppression comments no finding consumed — the fix landed, so
    # the comment should go (reported like stale baseline entries).
    unused_suppressions: list[tuple[str, int]] = dataclasses.field(
        default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            # An explicitly named file is linted regardless of
            # extension (extensionless scripts included) — skipping it
            # silently would let the CI gate pass while checking
            # nothing; non-Python content surfaces as a syntax-error
            # finding.
            yield path
            continue
        if not os.path.isdir(path):
            # A typo'd path silently yielding nothing would let the CI
            # gate pass while checking nothing — fail loudly instead.
            raise FileNotFoundError(
                f"lint path does not exist: {path!r}")
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def parse_suppressions(
        source: str) -> tuple[dict[int, set[str]], list[int]]:
    """Line → suppressed rule names, plus lines whose suppression has
    no ``-- why`` justification (reported, not honored silently).

    Tokenized, not line-scanned: only real ``#`` comments count, so a
    suppression example quoted inside a docstring is inert."""
    by_line: dict[int, set[str]] = {}
    unjustified: list[int] = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return by_line, unjustified  # unparseable: no suppressions
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        by_line[i] = names
        if not (m.group(2) or "").strip():
            unjustified.append(i)
    return by_line, unjustified


def load_baseline(path: str) -> list[dict]:
    """Validated baseline entries.  Raises ValueError on a malformed
    file or an entry missing its justification."""
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(f"{path}: baseline must be a JSON list")
    for i, e in enumerate(entries):
        for field in ("path", "rule", "code", "reason"):
            if not isinstance(e.get(field), str) or not e[field].strip():
                raise ValueError(
                    f"{path}: entry {i} needs a non-empty {field!r} "
                    "(every grandfathered finding carries its "
                    "justification)")
        if e["rule"] not in RULES and e["rule"] not in PROJECT_RULES:
            raise ValueError(
                f"{path}: entry {i} names unknown rule {e['rule']!r}")
    return entries


@dataclasses.dataclass
class _ParsedFile:
    """One file, parsed exactly once: the per-module pass, the project
    graph, and the suppression pass all share this."""
    path: str
    rel: str
    source: str
    ctx: ModuleContext | None          # None on syntax error
    error: Finding | None


def _parse_file(path: str, rel_path: str) -> _ParsedFile:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return _ParsedFile(
            path, rel_path, source, None,
            Finding(rel_path, e.lineno or 1, e.offset or 0,
                    "syntax-error", f"cannot parse: {e.msg}"))
    return _ParsedFile(path, rel_path, source,
                       ModuleContext(rel_path, source, tree), None)


def _module_findings(ctx: ModuleContext,
                     select: set[str] | None) -> list[Finding]:
    raw: list[Finding] = []
    for name, rule in RULES.items():
        if select is not None and name not in select:
            continue
        raw.extend(rule.check(ctx))
    return raw


def _podlint_config(manifest_path: str | None) -> PodlintConfig:
    return PodlintConfig(
        manifest_path=manifest_path or DEFAULT_MANIFEST)


def _apply_suppressions(
        source: str, rel_path: str, raw: list[Finding],
        select: set[str] | None
) -> tuple[list[Finding], int, list[int]]:
    """Suppression + bare-suppression + unused-suppression pass for
    one file's combined (module + project) findings.

    A suppression applies to any finding whose statement extent
    ``[line, end_line]`` covers the comment's line, so the idiomatic
    placement at the END of a multiline call works."""
    by_line, unjustified = parse_suppressions(source)
    kept: list[Finding] = []
    suppressed = 0
    used_lines: set[int] = set()
    for f_ in sorted(raw, key=lambda f_: (f_.line, f_.col, f_.rule)):
        hit = next(
            (ln for ln in range(f_.line, max(f_.end_line, f_.line) + 1)
             if "all" in by_line.get(ln, ())
             or f_.rule in by_line.get(ln, ())), None)
        if hit is not None:
            suppressed += 1
            used_lines.add(hit)
        else:
            kept.append(f_)
    for line in unjustified:
        code = source.splitlines()[line - 1].strip()
        kept.append(Finding(
            rel_path, line, 0, "bare-suppression",
            "suppression without a `-- <why>` justification: silenced "
            "findings must stay auditable", code, line))
    # Unused-suppression audit only makes sense with every rule armed:
    # under --select, other rules' suppressions are legitimately idle.
    unused = [] if select is not None else \
        [ln for ln in by_line
         if ln not in used_lines and ln not in unjustified]
    return kept, suppressed, unused


def lint_file(path: str, rel_path: str,
              select: set[str] | None = None,
              manifest_path: str | None = None
              ) -> tuple[list[Finding], int, list[int]]:
    """(actionable findings, suppressed count, unused-suppression
    lines) for one file.  Syntax errors surface as a finding on the
    offending line rather than crashing the whole run.

    The interprocedural rules run too, over a one-module project —
    cross-module behaviour needs ``run_paths`` on a directory."""
    pf = _parse_file(path, rel_path)
    if pf.ctx is None:
        return [pf.error], 0, []
    raw = _module_findings(pf.ctx, select)
    graph = ProjectGraph([pf.ctx])
    raw.extend(run_project_rules(graph, select,
                                 _podlint_config(manifest_path)))
    return _apply_suppressions(pf.source, rel_path, raw, select)


def run_paths(paths: Iterable[str], baseline_path: str | None = None,
              select: set[str] | None = None,
              root: str | None = None,
              manifest_path: str | None = None) -> LintResult:
    """Lint every .py under ``paths``: per-module rules, then the
    interprocedural podlint pass over the whole parsed set, then
    suppressions + baseline on the merged findings.  Each file is
    parsed exactly once."""
    root = root or os.getcwd()
    baseline = load_baseline(baseline_path) if baseline_path and \
        os.path.exists(baseline_path) else []
    parsed: list[_ParsedFile] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        rel = rel.replace(os.sep, "/")
        parsed.append(_parse_file(path, rel))

    raw_by_rel: dict[str, list[Finding]] = {}
    for pf in parsed:
        if pf.ctx is None:
            raw_by_rel.setdefault(pf.rel, []).append(pf.error)
        else:
            raw_by_rel.setdefault(pf.rel, []).extend(
                _module_findings(pf.ctx, select))
    graph = ProjectGraph([pf.ctx for pf in parsed if pf.ctx])
    for f_ in run_project_rules(graph, select,
                                _podlint_config(manifest_path)):
        raw_by_rel.setdefault(f_.path, []).append(f_)

    matched: set[int] = set()
    findings: list[Finding] = []
    unused_supp: list[tuple[str, int]] = []
    suppressed = 0
    for pf in parsed:
        raw = raw_by_rel.get(pf.rel, [])
        if pf.ctx is None:
            kept, supp, unused = raw, 0, []
        else:
            kept, supp, unused = _apply_suppressions(
                pf.source, pf.rel, raw, select)
        suppressed += supp
        unused_supp.extend((pf.rel, ln) for ln in sorted(unused))
        for f_ in kept:
            hit = next(
                (i for i, e in enumerate(baseline)
                 if i not in matched and e["path"] == f_.path
                 and e["rule"] == f_.rule and e["code"] == f_.code),
                None)
            if hit is not None:
                matched.add(hit)
            else:
                findings.append(f_)
    stale = [e for i, e in enumerate(baseline) if i not in matched]
    return LintResult(findings, suppressed, len(matched), stale,
                      len(parsed), unused_supp)


def write_baseline(result: LintResult, path: str,
                   prior: Iterable[dict] = ()) -> int:
    """Snapshot current findings as baseline entries; returns how many
    meta-findings were NOT grandfathered.

    ``prior`` (the previous baseline's entries) carries hand-written
    justifications forward for findings whose (path, rule, code)
    fingerprint is unchanged; new entries are stamped TODO —
    ``load_baseline`` accepts them (non-empty) but the PR review should
    replace each with the real justification.  Meta-findings
    (``bare-suppression``, ``syntax-error``) are skipped: they are not
    grandfatherable (``load_baseline`` rejects their rule names) and
    must be fixed at the source."""
    kept_reasons = {(e["path"], e["rule"], e["code"]): e["reason"]
                    for e in prior}
    entries = []
    skipped = 0
    for f_ in result.findings:
        if f_.rule not in RULES and f_.rule not in PROJECT_RULES:
            skipped += 1
            continue
        entries.append({
            "path": f_.path, "rule": f_.rule, "code": f_.code,
            "reason": kept_reasons.get(
                (f_.path, f_.rule, f_.code),
                "TODO: justify this grandfathered finding")})
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=2, sort_keys=True)
        f.write("\n")
    return skipped
