"""podlint project graph — the interprocedural layer under jaxlint.

``rules.py`` looks at one module at a time; this module builds a
whole-project view from the same parse trees: a function table with
qualified ids, call + reference edges between project functions,
thread entry points (``threading.Thread(target=...)`` and watchdog
``add_monitor`` registrations, including the factory-closure idiom
``add_monitor(commit_monitor(...))``), and a top-level import graph.
The project rules in ``podrules.py`` consume it.

Everything here is pure AST work — the code under analysis is never
imported, and this module (like the rest of the analysis package)
must never import jax.

Resolution strategy, in decreasing confidence (precision over recall,
the package-wide philosophy — an unresolvable call simply adds no
edge):

* plain names through the lexical scope chain (nested defs, then
  module functions/classes, then ``from mod import f`` aliases);
* ``self.m()`` / ``cls.m()`` through the enclosing class, walking
  in-project base classes;
* ``alias.f()`` where ``alias`` binds a project module;
* ``x.m()`` where ``x = SomeProjectClass(...)`` earlier in the same
  function body (single-assignment local type inference);
* last, a unique-method fallback: if exactly one project class
  defines method ``m`` and ``m`` is not an ultra-common name, an
  unresolved ``obj.m()`` binds to it.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator

from .rules import ModuleContext, _iter_defs, _own_body_walk, _qualname

# Host-level multihost collectives.  ``assert_equal`` is unambiguous
# in this tree (numpy.testing is not used in lint scope) but is still
# guarded against numpy-prefixed quals below.  In-graph collectives
# (psum/pmean inside shard_map) are deliberately out of scope: they
# are symmetric by construction once dispatch is symmetric.
COLLECTIVE_ATTRS = {"process_allgather", "broadcast_one_to_all",
                    "sync_global_devices", "assert_equal"}
_COLLECTIVE_PREFIX = "jax.experimental.multihost_utils."

GATE_NAME = "raise_if_degraded"

# Method names too generic for the unique-method fallback: binding
# ``q.get()`` to some project class just because only one class in
# scope happens to define ``get`` would wire stdlib queues/dicts/etc.
# into the call graph.
_COMMON_METHODS = {
    "get", "put", "set", "add", "pop", "append", "extend", "update",
    "remove", "clear", "copy", "keys", "values", "items", "start",
    "stop", "join", "close", "open", "run", "read", "write", "flush",
    "send", "recv", "wait", "notify", "acquire", "release", "submit",
    "result", "cancel", "load", "save", "restore", "reset", "next",
    "serve_forever", "shutdown", "check", "note", "observe",
    "render", "name", "fileno", "encode", "decode", "format", "count",
    "index", "sort", "split", "strip", "item", "tolist", "mean",
}


def module_name(rel_path: str) -> str:
    """``imagent_tpu/data/stream.py`` → ``imagent_tpu.data.stream``;
    a package ``__init__.py`` maps to the package itself."""
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    p = p.replace("/", ".").replace(os.sep, ".")
    if p.endswith(".__init__"):
        p = p[: -len(".__init__")]
    return p


@dataclasses.dataclass
class FuncInfo:
    """One analysis scope: a def/method, or a module's top level."""
    fid: str                 # "pkg.mod:C.m", "pkg.mod:<module>"
    modname: str
    qualpath: str            # "f", "C.m", "f.<locals>.g", "<module>"
    node: ast.AST            # FunctionDef/AsyncFunctionDef, or Module
    parent: str | None       # enclosing scope's fid
    cls: str | None = None   # qualified class path when a method


@dataclasses.dataclass
class Edge:
    caller: str
    callee: str
    pos: tuple[int, int]     # site position inside the caller
    kind: str                # "call" (invoked) | "ref" (passed/stored)
    node: ast.AST


@dataclasses.dataclass
class ThreadEntry:
    fid: str                 # the function that runs off-main-thread
    via: str                 # "thread-target" | "monitor"
    site_fid: str            # where the registration happens
    node: ast.AST            # the registration call


@dataclasses.dataclass
class CollectiveSite:
    fid: str
    node: ast.Call
    name: str                # the collective primitive's attr name


class _ClassEntry:
    def __init__(self) -> None:
        self.module: str = ""               # owning module
        self.methods: dict[str, str] = {}   # name -> fid
        self.bases: list[str] = []          # qualified "mod.C" names


class ProjectGraph:
    """Import graph + call graph over a set of parsed modules."""

    def __init__(self, contexts: list[ModuleContext]):
        self.modules: dict[str, ModuleContext] = {
            module_name(c.rel_path): c for c in contexts}
        self.functions: dict[str, FuncInfo] = {}
        self.edges: list[Edge] = []
        self.out_edges: dict[str, list[Edge]] = {}
        self.in_edges: dict[str, list[Edge]] = {}
        self.thread_entries: list[ThreadEntry] = []
        self.collective_sites: list[CollectiveSite] = []
        # modname -> [(imported module name, anchoring AST node)], from
        # TOP-LEVEL imports only: function-scope (lazy) imports are the
        # sanctioned jax-avoidance idiom and do not run at import time.
        self.imports: dict[str, list[tuple[str, ast.AST]]] = {}

        self._mod_funcs: dict[str, dict[str, str]] = {}    # top-level defs
        self._mod_classes: dict[str, dict[str, str]] = {}  # name -> "mod.C"
        self._classes: dict[str, _ClassEntry] = {}         # "mod.C"
        self._nested: dict[str, dict[str, str]] = {}       # fid -> kids
        self._direct_gates: dict[str, list[tuple[int, int]]] = {}
        self._gate_pos: dict[str, list[tuple[int, int]]] = {}
        self._methods_by_name: dict[str, list[str]] = {}

        for mod, ctx in self.modules.items():
            self._collect_defs(mod, ctx)
        for mod, ctx in self.modules.items():
            self._collect_imports(mod, ctx)
            self._resolve_bases(mod, ctx)
        for mod, ctx in self.modules.items():
            self._collect_edges(mod, ctx)
        for e in self.edges:
            self.out_edges.setdefault(e.caller, []).append(e)
            self.in_edges.setdefault(e.callee, []).append(e)

    # ------------------------------------------------------------ tables

    def _collect_defs(self, mod: str, ctx: ModuleContext) -> None:
        root_fid = f"{mod}:<module>"
        self.functions[root_fid] = FuncInfo(
            root_fid, mod, "<module>", ctx.tree, None)
        self._mod_funcs[mod] = {}
        self._mod_classes[mod] = {}

        def visit(node: ast.AST, qual: list[str], parent: str,
                  cls: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    path = ".".join([*qual, child.name])
                    fid = f"{mod}:{path}"
                    self.functions[fid] = FuncInfo(
                        fid, mod, path, child, parent, cls)
                    if not qual:
                        self._mod_funcs[mod][child.name] = fid
                    self._nested.setdefault(parent, {})[child.name] = fid
                    visit(child, [*qual, child.name, "<locals>"],
                          fid, None)
                elif isinstance(child, ast.ClassDef):
                    cpath = ".".join([*qual, child.name])
                    ckey = f"{mod}.{cpath}"
                    entry = self._classes.setdefault(ckey, _ClassEntry())
                    entry.module = mod
                    entry.bases = [
                        b for b in (
                            _qualname(base, ctx.aliases)
                            for base in child.bases) if b]
                    if not qual:
                        self._mod_classes[mod][child.name] = ckey
                    for m in child.body:
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            mpath = f"{cpath}.{m.name}"
                            fid = f"{mod}:{mpath}"
                            self.functions[fid] = FuncInfo(
                                fid, mod, mpath, m, parent, cpath)
                            entry.methods[m.name] = fid
                            self._methods_by_name.setdefault(
                                m.name, []).append(fid)
                            visit(m, [cpath, m.name, "<locals>"],
                                  fid, None)
                        else:
                            visit_cls_stmt(m, qual, parent, cpath)
                else:
                    visit(child, qual, parent, cls)

        def visit_cls_stmt(node: ast.AST, qual: list[str], parent: str,
                           cpath: str) -> None:
            # Non-def statements in a class body run at import time in
            # the module pseudo-scope; nested classes recurse.
            visit(node, [cpath], parent, cpath)

        visit(ctx.tree, [], root_fid, None)

    def _resolve_bases(self, mod: str, ctx: ModuleContext) -> None:
        for ckey, entry in list(self._classes.items()):
            if entry.module != mod:
                continue
            resolved = []
            for b in entry.bases:
                got = self._resolve_class_name(mod, b)
                if got:
                    resolved.append(got)
            entry.bases = resolved

    def _resolve_class_name(self, mod: str, dotted: str) -> str | None:
        if dotted in self._mod_classes.get(mod, {}):
            return self._mod_classes[mod][dotted]
        if dotted in self._classes:
            return dotted
        # "pkg.mod.C" via an import alias
        head, _, tail = dotted.rpartition(".")
        if head in self.modules and tail in self._mod_classes.get(
                head, {}):
            return self._mod_classes[head][tail]
        return None

    # ----------------------------------------------------------- imports

    def _collect_imports(self, mod: str, ctx: ModuleContext) -> None:
        out: list[tuple[str, ast.AST]] = []

        def is_type_checking(test: ast.AST) -> bool:
            q = _qualname(test, ctx.aliases)
            return q is not None and q.endswith("TYPE_CHECKING")

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # lazy imports: the sanctioned idiom
                if isinstance(child, ast.If) and \
                        is_type_checking(child.test):
                    continue
                if isinstance(child, ast.Import):
                    for a in child.names:
                        out.append((a.name, child))
                elif isinstance(child, ast.ImportFrom):
                    base = child.module or ""
                    if child.level:
                        pkg = mod.split(".")
                        if os.path.basename(
                                ctx.rel_path) != "__init__.py":
                            pkg = pkg[:-1]
                        pkg = pkg[: len(pkg) - child.level + 1]
                        base = ".".join(pkg + ([base] if base else []))
                    if base:
                        out.append((base, child))
                    for a in child.names:
                        sub = f"{base}.{a.name}" if base else a.name
                        # "from pkg import submodule" imports a module;
                        # "from pkg.mod import fn" does not add an edge
                        # beyond pkg.mod itself.
                        if sub in self.modules or sub.split(
                                ".")[0] in ("jax", "jaxlib"):
                            out.append((sub, child))
                else:
                    walk(child)

        walk(ctx.tree)
        self.imports[mod] = out

    def import_closure(self, mod: str) -> dict[str, list[str]]:
        """Transitive top-level imports of ``mod`` restricted to
        project modules, each mapped to the chain of project modules
        that reaches it (``[mod, ..., target]``).  Importing a module
        executes every ancestor package ``__init__`` too, so those are
        folded in at each step."""
        chains: dict[str, list[str]] = {}
        stack: list[tuple[str, list[str]]] = []
        for m in self._with_ancestors(mod):
            chains[m] = [m] if m == mod else [mod, m]
            stack.append((m, chains[m]))
        while stack:
            cur, chain = stack.pop()
            for target, _node in self.imports.get(cur, ()):
                for t in self._with_ancestors(target):
                    if t in self.modules and t not in chains:
                        chains[t] = chain + [t]
                        stack.append((t, chains[t]))
        return chains

    def _with_ancestors(self, mod: str) -> list[str]:
        parts = mod.split(".")
        return [".".join(parts[: i + 1]) for i in range(len(parts))]

    # ------------------------------------------------------------- edges

    def _collect_edges(self, mod: str, ctx: ModuleContext) -> None:
        for fid, info in self.functions.items():
            if info.modname != mod:
                continue
            body = list(
                _own_body_walk(info.node) if info.qualpath !=
                "<module>" else self._module_scope_walk(info.node))
            local_types = self._local_types(mod, ctx, body)
            for node in body:
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == GATE_NAME) \
                        or (isinstance(f, ast.Name) and f.id == GATE_NAME):
                    self._direct_gates.setdefault(fid, []).append(
                        (node.lineno, node.col_offset))
                self._record_call(mod, ctx, info, node, local_types)

    def _module_scope_walk(self, tree: ast.AST) -> Iterator[ast.AST]:
        """Module top level, descending into class bodies (they run at
        import) but not into function bodies."""
        stack = list(ast.iter_child_nodes(tree))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _local_types(self, mod: str, ctx: ModuleContext,
                     body: list[ast.AST]) -> dict[str, str]:
        """``x = SomeProjectClass(...)`` single-assignment inference
        within one function body: name -> qualified class."""
        types: dict[str, str] = {}
        for node in body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                q = _qualname(node.value.func, ctx.aliases)
                ckey = self._resolve_class_name(mod, q) if q else None
                name = node.targets[0].id
                if ckey:
                    if name in types and types[name] != ckey:
                        types[name] = ""  # conflicting: give up
                    elif name not in types:
                        types[name] = ckey
                else:
                    types.setdefault(name, "")
        return {k: v for k, v in types.items() if v}

    def _record_call(self, mod: str, ctx: ModuleContext, info: FuncInfo,
                     node: ast.Call,
                     local_types: dict[str, str]) -> None:
        pos = (node.lineno, node.col_offset)
        callee = self._resolve_callable(mod, ctx, info, node.func,
                                        local_types)
        if callee:
            self.edges.append(Edge(info.fid, callee, pos, "call", node))

        # Collective primitive site?
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in COLLECTIVE_ATTRS:
            q = _qualname(node.func, ctx.aliases)
            if not (q and (q.startswith("numpy.")
                           or q.startswith("np."))):
                self.collective_sites.append(
                    CollectiveSite(info.fid, node, node.func.attr))
        else:
            q = _qualname(node.func, ctx.aliases)
            if q and q.startswith(_COLLECTIVE_PREFIX):
                self.collective_sites.append(
                    CollectiveSite(info.fid, node, q.rsplit(".", 1)[-1]))

        # Thread target / monitor registration.
        fq = _qualname(node.func, ctx.aliases)
        is_thread = fq == "threading.Thread" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "Thread")
        if is_thread:
            for kw in node.keywords:
                if kw.arg == "target":
                    t = self._resolve_callable(
                        mod, ctx, info, kw.value, local_types)
                    if t:
                        self.thread_entries.append(
                            ThreadEntry(t, "thread-target", info.fid,
                                        node))
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "add_monitor":
            for arg in node.args:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    t = self._resolve_callable(
                        mod, ctx, info, arg, local_types)
                    if t:
                        self.thread_entries.append(
                            ThreadEntry(t, "monitor", info.fid, node))
                elif isinstance(arg, ast.Call):
                    # Factory-closure idiom: add_monitor(make_check(..))
                    # — the factory's nested defs run off-thread.
                    t = self._resolve_callable(
                        mod, ctx, info, arg.func, local_types)
                    if t:
                        for kid in self._nested.get(t, {}).values():
                            self.thread_entries.append(
                                ThreadEntry(kid, "monitor", info.fid,
                                            node))

        # Reference edges: a project function passed as an argument
        # (functools.partial targets, Thread targets, callbacks).
        for arg in [*node.args,
                    *(kw.value for kw in node.keywords)]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                t = self._resolve_callable(mod, ctx, info, arg,
                                           local_types)
                if t:
                    self.edges.append(
                        Edge(info.fid, t,
                             (arg.lineno, arg.col_offset), "ref", arg))

    def _resolve_callable(self, mod: str, ctx: ModuleContext,
                          info: FuncInfo, expr: ast.AST,
                          local_types: dict[str, str]) -> str | None:
        if isinstance(expr, ast.Name):
            return self._resolve_plain_name(mod, info, expr.id,
                                            ctx)
        if not isinstance(expr, ast.Attribute):
            return None
        base, attr = expr.value, expr.attr
        # self.m() / cls.m() through the enclosing class (+ bases).
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            cls = self._enclosing_class(info)
            if cls:
                return self._lookup_method(f"{mod}.{cls}", attr)
            return None
        # x.m() where x was assigned a project-class instance.
        if isinstance(base, ast.Name) and base.id in local_types:
            return self._lookup_method(local_types[base.id], attr)
        q = _qualname(expr, ctx.aliases)
        if q:
            head, _, tail = q.rpartition(".")
            # alias.f() where alias binds a project module
            if head in self.modules:
                if tail in self._mod_funcs.get(head, {}):
                    return self._mod_funcs[head][tail]
                if tail in self._mod_classes.get(head, {}):
                    return self._lookup_method(
                        self._mod_classes[head][tail], "__init__")
            # Module.Class.method (rare static access)
            ckey = self._resolve_class_name(mod, head) if head else None
            if ckey:
                return self._lookup_method(ckey, attr)
            if q.split(".")[0] in ("numpy", "np", "jax", "os", "sys",
                                   "time", "json", "math", "logging",
                                   "threading", "queue", "subprocess"):
                return None
        # Unique-method fallback.
        if attr not in _COMMON_METHODS and not attr.startswith("__"):
            cands = self._methods_by_name.get(attr, ())
            if len(cands) == 1:
                return cands[0]
        return None

    def _resolve_plain_name(self, mod: str, info: FuncInfo, name: str,
                            ctx: ModuleContext) -> str | None:
        # Lexical chain: nested defs of enclosing scopes, innermost out.
        cur: FuncInfo | None = info
        while cur is not None:
            kids = self._nested.get(cur.fid, {})
            if name in kids:
                return kids[name]
            cur = self.functions.get(cur.parent) if cur.parent else None
        if name in self._mod_funcs.get(mod, {}):
            return self._mod_funcs[mod][name]
        if name in self._mod_classes.get(mod, {}):
            return self._lookup_method(
                self._mod_classes[mod][name], "__init__")
        dotted = ctx.aliases.get(name)
        if dotted and dotted != name:
            head, _, tail = dotted.rpartition(".")
            if head in self.modules:
                if tail in self._mod_funcs.get(head, {}):
                    return self._mod_funcs[head][tail]
                if tail in self._mod_classes.get(head, {}):
                    return self._lookup_method(
                        self._mod_classes[head][tail], "__init__")
        return None

    def _enclosing_class(self, info: FuncInfo) -> str | None:
        cur: FuncInfo | None = info
        while cur is not None:
            if cur.cls:
                return cur.cls
            cur = self.functions.get(cur.parent) if cur.parent else None
        return None

    def _lookup_method(self, ckey: str, attr: str) -> str | None:
        seen: set[str] = set()
        queue = [ckey]
        while queue:
            c = queue.pop(0)
            if c in seen:
                continue
            seen.add(c)
            entry = self._classes.get(c)
            if entry is None:
                continue
            if attr in entry.methods:
                return entry.methods[attr]
            queue.extend(entry.bases)
        return None

    # --------------------------------------------------------- analyses

    def gate_positions(self, fid: str) -> list[tuple[int, int]]:
        """Source positions of deadman-gate events inside ``fid``'s own
        body: direct ``raise_if_degraded`` calls plus calls into
        functions known (transitively) to gate."""
        cached = self._gate_pos.get(fid)
        if cached is not None:
            return cached
        gating = self.gating_functions()
        out = list(self._direct_gates.get(fid, ()))
        for e in self.out_edges.get(fid, ()):
            if e.kind == "call" and e.callee in gating:
                out.append(e.pos)
        out.sort()
        self._gate_pos[fid] = out
        return out

    def gating_functions(self) -> set[str]:
        """Functions that perform a deadman gate themselves or via a
        (transitive) direct call."""
        if not hasattr(self, "_gating"):
            direct = set(self._direct_gates)
            changed = True
            while changed:
                changed = False
                for e in self.edges:
                    if e.kind == "call" and e.callee in direct \
                            and e.caller not in direct:
                        direct.add(e.caller)
                        changed = True
            self._gating = direct
        return self._gating

    def collective_reaching(self) -> set[str]:
        """Functions from which a collective primitive is reachable
        through call/ref edges."""
        reach = {s.fid for s in self.collective_sites}
        changed = True
        while changed:
            changed = False
            for e in self.edges:
                if e.callee in reach and e.caller not in reach:
                    reach.add(e.caller)
                    changed = True
        return reach

    def entry_gated(self) -> dict[str, bool]:
        """Greatest fixpoint: fid -> True when EVERY path into the
        function passes a deadman gate first (either the caller gates
        before the call site, or the caller itself is entry-gated).
        Module top levels and thread entries are never entry-gated."""
        gated = {fid: True for fid in self.functions}
        pinned: set[str] = set()
        for fid, info in self.functions.items():
            if info.qualpath == "<module>" or not self.in_edges.get(fid):
                gated[fid] = False
                pinned.add(fid)
        for t in self.thread_entries:
            gated[t.fid] = False
            pinned.add(t.fid)
        gate_pos = {fid: self.gate_positions(fid)
                    for fid in self.functions}
        changed = True
        while changed:
            changed = False
            for fid in self.functions:
                if fid in pinned or not gated[fid]:
                    continue
                ok = True
                for e in self.in_edges.get(fid, ()):
                    before = any(p < e.pos for p in gate_pos[e.caller])
                    if not (before or gated[e.caller]):
                        ok = False
                        break
                if not ok:
                    gated[fid] = False
                    changed = True
        return gated

    def ungated_path(self, fid: str,
                     gated: dict[str, bool]) -> list[str]:
        """An example call chain root → ... → ``fid`` along which no
        gate is passed, for finding messages."""
        gate_pos: dict[str, list[tuple[int, int]]] = {}
        path = [fid]
        seen = {fid}
        cur = fid
        while True:
            info = self.functions.get(cur)
            nxt = None
            for e in self.in_edges.get(cur, ()):
                if e.caller in seen:
                    continue
                pos = gate_pos.setdefault(
                    e.caller, self.gate_positions(e.caller))
                if not any(p < e.pos for p in pos) and \
                        not gated.get(e.caller, False):
                    nxt = e.caller
                    break
            if nxt is None or info is None:
                break
            path.append(nxt)
            seen.add(nxt)
            cur = nxt
            if self.functions[cur].qualpath == "<module>":
                break
        return list(reversed(path))

    def reachable_from(self, fids: list[str]) -> dict[str, list[str]]:
        """BFS over call+ref edges: fid -> example chain from one of
        the given entry points."""
        chains: dict[str, list[str]] = {f: [f] for f in fids}
        queue = list(fids)
        while queue:
            cur = queue.pop(0)
            for e in self.out_edges.get(cur, ()):
                if e.callee not in chains:
                    chains[e.callee] = chains[cur] + [e.callee]
                    queue.append(e.callee)
        return chains
