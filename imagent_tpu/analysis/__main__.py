"""``python -m imagent_tpu.analysis`` — the jaxlint CI gate."""

import sys

from imagent_tpu.analysis.cli import main

sys.exit(main())
