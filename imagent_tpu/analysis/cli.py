"""jaxlint CLI: ``python -m imagent_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/configuration error — so
``make lint`` is a hard CI gate.
"""

from __future__ import annotations

import argparse
import json
import sys

import os

from imagent_tpu.analysis.podrules import PROJECT_RULES
from imagent_tpu.analysis.rules import RULES
from imagent_tpu.analysis.runner import (
    DEFAULT_BASELINE, load_baseline, run_paths, write_baseline,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m imagent_tpu.analysis",
        description="jaxlint: JAX/TPU-aware static analysis "
                    "(docs/STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   default=["imagent_tpu", "benchmarks"],
                   help="files/directories to lint (default: "
                        "imagent_tpu benchmarks, from the repo root)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="grandfathered-findings file (default: "
                        "imagent_tpu/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="snapshot current findings into --baseline "
                        "(reasons stamped TODO — edit before commit)")
    p.add_argument("--select", metavar="RULE[,RULE...]",
                   help="run only these rules (per-module or podlint)")
    p.add_argument("--jaxfree-manifest", metavar="PATH",
                   help="jax-free module manifest for the "
                        "jax-free-violation rule (default: "
                        "imagent_tpu/analysis/jaxfree.json)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text",
                   help="output format: human text (default) or a "
                        "stable machine-readable JSON document")
    p.add_argument("--list-rules", action="store_true",
                   help="print each rule and why it bites on TPU")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="summary line only")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        both = {**RULES, **PROJECT_RULES}
        width = max(len(n) for n in both)
        for name, rule in sorted(RULES.items()):
            print(f"{name:<{width}}  {rule.doc}")
        for name, rule in sorted(PROJECT_RULES.items()):
            print(f"{name:<{width}}  [podlint] {rule.doc}")
        return 0
    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        unknown = select - set(RULES) - set(PROJECT_RULES)
        if unknown:
            print(f"jaxlint: unknown rule(s): {', '.join(sorted(unknown))}"
                  f" (see --list-rules)", file=sys.stderr)
            return 2
    # --write-baseline snapshots the complete current state, so the
    # existing baseline must not pre-filter what gets written.
    if args.write_baseline and select is not None:
        # A partial-rule snapshot would silently drop every other
        # rule's grandfathered entries (and their justifications).
        print("jaxlint: --write-baseline cannot be combined with "
              "--select: the baseline is a whole-tree snapshot",
              file=sys.stderr)
        return 2
    baseline = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    try:
        result = run_paths(args.paths, baseline_path=baseline,
                           select=select,
                           manifest_path=args.jaxfree_manifest)
    except (ValueError, OSError) as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        prior: list = []
        if os.path.exists(args.baseline):
            try:  # carry hand-written reasons forward across rewrites
                prior = load_baseline(args.baseline)
            except ValueError:
                prior = []  # malformed old file: rewrite from scratch
        skipped = write_baseline(result, args.baseline, prior)
        n = len(result.findings) - skipped
        print(f"jaxlint: wrote {n} baseline "
              f"entr{'y' if n == 1 else 'ies'} to "
              f"{args.baseline} — fill in each TODO reason")
        if skipped:
            print(f"jaxlint: {skipped} meta-finding(s) "
                  "(bare-suppression / syntax-error) NOT grandfathered "
                  "— fix them at the source", file=sys.stderr)
        return 0
    if args.format == "json":
        # Stable machine-readable schema (format_version bumps on any
        # breaking change) for CI and regress-style tooling.
        doc = {
            "format_version": 1,
            "files_checked": result.files_checked,
            "findings": [
                {"path": f.path, "line": f.line, "col": f.col,
                 "rule": f.rule, "message": f.message, "code": f.code}
                for f in result.findings],
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "stale_baseline": result.stale_baseline,
            "unused_suppressions": [
                {"path": p, "line": ln}
                for p, ln in result.unused_suppressions],
            "ok": result.ok,
        }
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0 if result.ok else 1
    if not args.quiet:
        for f in result.findings:
            print(f.render())
        for e in result.stale_baseline:
            print(f"jaxlint: stale baseline entry ({e['rule']} @ "
                  f"{e['path']}): no longer matches — delete it",
                  file=sys.stderr)
        for spath, sline in result.unused_suppressions:
            print(f"jaxlint: unused suppression at {spath}:{sline}: "
                  "no finding matches — delete the comment",
                  file=sys.stderr)
    print(f"jaxlint: {len(result.findings)} finding(s) "
          f"({result.baselined} baselined, {result.suppressed} "
          f"suppressed) across {result.files_checked} file(s)")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
