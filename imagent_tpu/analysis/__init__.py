"""jaxlint: JAX/TPU-aware static analysis (the CI lint gate).

An AST-walking lint framework with rules for the defect classes that
only surface at pod scale — host syncs inside jitted bodies, PRNG key
reuse, recompile hazards, nondeterministic pytree ordering, missing
buffer donation, wire-format dtype drift, and unsynced benchmark
timing.  Run ``python -m imagent_tpu.analysis`` (or ``make lint``);
rules and workflow are documented in docs/STATIC_ANALYSIS.md.

Deliberately jax-free: the linter parses source, it never imports the
code under analysis, so it runs in milliseconds and can gate CI before
any backend exists.
"""

from imagent_tpu.analysis.rules import RULES, Finding, Rule  # noqa: F401
from imagent_tpu.analysis.runner import (  # noqa: F401
    LintResult, lint_file, run_paths,
)
