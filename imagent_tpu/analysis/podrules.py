"""podlint project rules — interprocedural checks over ProjectGraph.

These close the blind spot ``docs/STATIC_ANALYSIS.md`` used to record
("per-module and mostly per-function"): every costly review-caught
defect class in this repo's history has been a cross-function
collective-discipline violation, and each rule here encodes one of
them.  Same philosophy as ``rules.py``: precision over recall, empty
baseline, suppressions carry justifications.

Rules live in their own registry (``PROJECT_RULES``) so the
per-module registry keeps its exact shape for existing tooling.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Callable, Iterator

from .graph import ProjectGraph
from .rules import (Finding, _HOST_FETCH_CALLS, _HOST_FETCH_METHODS,
                    _TRACER_COERCIONS, _own_body_walk, _param_names,
                    _qualname, _rooted_at_param)

DEFAULT_MANIFEST = os.path.join(os.path.dirname(__file__),
                                "jaxfree.json")


@dataclasses.dataclass
class PodlintConfig:
    """Knobs the project rules need beyond the graph itself."""
    manifest: dict | None = None       # parsed jaxfree.json
    manifest_path: str | None = None   # where it came from (messages)


@dataclasses.dataclass
class ProjectRule:
    name: str
    doc: str
    check: Callable[[ProjectGraph, PodlintConfig], Iterator[Finding]]


PROJECT_RULES: dict[str, ProjectRule] = {}


def project_rule(name: str, doc: str):
    def deco(fn):
        PROJECT_RULES[name] = ProjectRule(name, doc, fn)
        return fn
    return deco


def load_manifest(path: str) -> dict:
    """Parsed + validated jax-free manifest."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    mods = data.get("modules")
    if not isinstance(mods, list) or \
            not all(isinstance(m, str) and m for m in mods):
        raise ValueError(
            f"{path}: 'modules' must be a list of dotted module names")
    return data


def run_project_rules(graph: ProjectGraph,
                      select: set[str] | None = None,
                      config: PodlintConfig | None = None
                      ) -> list[Finding]:
    config = config or PodlintConfig()
    out: list[Finding] = []
    for name, rule in PROJECT_RULES.items():
        if select is not None and name not in select:
            continue
        out.extend(rule.check(graph, config))
    return out


def _short(fid: str) -> str:
    """"imagent_tpu.engine:run" → "engine:run" for readable chains."""
    mod, _, qual = fid.partition(":")
    return f"{mod.rsplit('.', 1)[-1]}:{qual}"


def _site_finding(graph: ProjectGraph, fid: str, node: ast.AST,
                  rule: str, message: str) -> Finding:
    info = graph.functions[fid]
    return graph.modules[info.modname].finding(node, rule, message)


# --------------------------------------------------------------------------
# Rule 1: ungated-collective
# --------------------------------------------------------------------------

@project_rule(
    "ungated-collective",
    "a multihost collective reachable without passing "
    "deadman.raise_if_degraded — a degraded pod hangs on it forever")
def check_ungated_collective(graph: ProjectGraph,
                             config: PodlintConfig
                             ) -> Iterator[Finding]:
    """Replaces the PR 7/14 hand audits.  A collective site is safe
    when a gate event precedes it in the same function body, or when
    every call path into its function passes a gate first (the
    entry-gated fixpoint).  Module top levels and thread entries are
    never entry-gated."""
    gated = graph.entry_gated()
    gate_pos = {fid: graph.gate_positions(fid)
                for fid in sorted({s.fid
                                   for s in graph.collective_sites})}
    for site in graph.collective_sites:
        pos = (site.node.lineno, site.node.col_offset)
        if any(p < pos for p in gate_pos[site.fid]):
            continue
        if gated.get(site.fid, False):
            continue
        chain = " -> ".join(
            _short(f) for f in graph.ungated_path(site.fid, gated))
        yield _site_finding(
            graph, site.fid, site.node, "ungated-collective",
            f"multihost collective `{site.name}` is reachable without "
            f"a deadman gate (ungated path: {chain}); call "
            "deadman.raise_if_degraded() before it so a degraded pod "
            "takes the exit ramp instead of hanging on a dead peer")


# --------------------------------------------------------------------------
# Rule 2: asymmetric-collective
# --------------------------------------------------------------------------

_RANK_NAMES = {"rank", "is_master", "master", "is_lead", "lead",
               "leader", "is_coordinator", "is_primary", "local_rank"}


def _is_rank_conditional(test: ast.AST, aliases: dict) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and (
                n.id in _RANK_NAMES or n.id.endswith("_rank")):
            return True
        target = n.func if isinstance(n, ast.Call) else n
        if isinstance(target, ast.Attribute):
            q = _qualname(target, aliases)
            if q and ("process_index" in q or q.endswith(".rank")
                      or "is_master" in q):
                return True
    return False


def _terminates(stmts: list[ast.stmt]) -> bool:
    return any(isinstance(s, (ast.Return, ast.Raise)) for s in stmts)


@project_rule(
    "asymmetric-collective",
    "a collective reachable only under a rank-conditional branch — "
    "the other ranks block forever (split-brain hang)")
def check_asymmetric_collective(graph: ProjectGraph,
                                config: PodlintConfig
                                ) -> Iterator[Finding]:
    """The PR 5 defect class: a collective (or a call into a
    collective-reaching function) under ``if process_index() == 0:``
    with no all-ranks counterpart in the other branch, or after a
    rank-guarded early return."""
    reach = graph.collective_reaching()
    prim_nodes = {id(s.node) for s in graph.collective_sites}
    for fid, info in graph.functions.items():
        ctx = graph.modules[info.modname]
        ish: dict[int, tuple[ast.Call, str]] = {}
        for s in graph.collective_sites:
            if s.fid == fid:
                ish[id(s.node)] = (s.node, f"collective `{s.name}`")
        for e in graph.out_edges.get(fid, ()):
            if e.kind == "call" and e.callee in reach \
                    and id(e.node) not in ish \
                    and id(e.node) not in prim_nodes:
                ish[id(e.node)] = (
                    e.node,
                    f"call into collective-reaching "
                    f"`{_short(e.callee)}`")
        if not ish:
            continue

        root = info.node if info.qualpath != "<module>" else None
        if root is None:
            continue

        def branch_has_ish(stmts: list[ast.stmt]) -> bool:
            for s in stmts:
                for n in ast.walk(s):
                    if id(n) in ish:
                        return True
            return False

        early_returns: list[ast.If] = []
        sites: list[tuple[ast.Call, str, list[tuple[ast.If, str]]]] = []

        def walk(node: ast.AST,
                 conds: list[tuple[ast.If, str]]) -> None:
            if id(node) in ish:
                n, why = ish[id(node)]
                sites.append((n, why, list(conds)))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.If):
                walk(node.test, conds)
                rc = _is_rank_conditional(node.test, ctx.aliases)
                if rc and _terminates(node.body) and not node.orelse:
                    early_returns.append(node)
                tag = "rank" if rc else "plain"
                for s in node.body:
                    walk(s, conds + [(node, f"body:{tag}")])
                for s in node.orelse:
                    walk(s, conds + [(node, f"orelse:{tag}")])
                return
            for child in ast.iter_child_nodes(node):
                walk(child, conds)

        for stmt in root.body:
            walk(stmt, [])

        seen: set[tuple[int, int]] = set()
        for node, why, conds in sites:
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            seen.add(key)
            guard = next(
                ((ifn, branch) for ifn, branch in reversed(conds)
                 if branch.endswith(":rank")), None)
            if guard is not None:
                ifn, branch = guard
                other = ifn.orelse if branch.startswith("body") \
                    else ifn.body
                if not branch_has_ish(other):
                    yield _site_finding(
                        graph, fid, node, "asymmetric-collective",
                        f"{why} runs only under the rank-conditional "
                        f"branch at line {ifn.lineno} with no "
                        "collective counterpart on the other ranks — "
                        "they block in the next collective forever "
                        "(split-brain hang); hoist the collective out "
                        "of the branch or give every rank a matching "
                        "call")
                continue
            for ifn in early_returns:
                end = getattr(ifn, "end_lineno", ifn.lineno)
                if node.lineno > end:
                    yield _site_finding(
                        graph, fid, node, "asymmetric-collective",
                        f"{why} executes only on ranks that survive "
                        f"the rank-guarded early return at line "
                        f"{ifn.lineno} — the returning ranks never "
                        "reach it and the rest hang; move the "
                        "collective above the guard or make the "
                        "guard symmetric")
                    break


# --------------------------------------------------------------------------
# Rule 3: collective-in-thread
# --------------------------------------------------------------------------

@project_rule(
    "collective-in-thread",
    "a multihost collective reachable from a Thread target or "
    "registered monitor — collectives must stay on the main thread")
def check_collective_in_thread(graph: ProjectGraph,
                               config: PodlintConfig
                               ) -> Iterator[Finding]:
    """Static complement of the runtime collective fence: committer
    threads, monitors, and heartbeat writers run exactly when the
    main thread may be wedged in a collective, so a second collective
    from one of them deadlocks the coordination service."""
    entries = {t.fid: t for t in graph.thread_entries}
    if not entries:
        return
    chains = graph.reachable_from(list(entries))
    for site in graph.collective_sites:
        chain = chains.get(site.fid)
        if chain is None:
            continue
        entry = entries[chain[0]]
        path = " -> ".join(_short(f) for f in chain)
        yield _site_finding(
            graph, site.fid, site.node, "collective-in-thread",
            f"multihost collective `{site.name}` is reachable from "
            f"off-main-thread entry point `{_short(entry.fid)}` "
            f"({entry.via} registered in `{_short(entry.site_fid)}`): "
            f"{path}; background threads are collective-free by "
            "contract — return a verdict to the main thread instead")


# --------------------------------------------------------------------------
# Rule 4: jax-free-violation
# --------------------------------------------------------------------------

@project_rule(
    "jax-free-violation",
    "a module declared jax-free in analysis/jaxfree.json whose "
    "top-level import closure reaches jax")
def check_jax_free(graph: ProjectGraph,
                   config: PodlintConfig) -> Iterator[Finding]:
    """Single source of truth for the no-device-handles contract:
    modules on the fatal-exit, per-step, decode-host, and
    committer-thread paths must be importable without pulling the JAX
    runtime.  Function-scope (lazy) imports are the sanctioned escape
    hatch and are ignored by construction.  Manifest entries absent
    from the linted tree are skipped — the consolidated import test
    (tests/test_jaxfree.py) catches genuinely stale entries."""
    manifest = config.manifest
    if manifest is None and config.manifest_path and \
            os.path.exists(config.manifest_path):
        manifest = load_manifest(config.manifest_path)
    if not manifest:
        return
    where = config.manifest_path or "the jax-free manifest"
    reported: set[tuple[str, int]] = set()
    for declared in manifest.get("modules", ()):
        if declared not in graph.modules:
            continue
        chains = graph.import_closure(declared)
        for mod, chain in sorted(chains.items()):
            for target, node in graph.imports.get(mod, ()):
                if target.split(".")[0] not in ("jax", "jaxlib"):
                    continue
                key = (mod, getattr(node, "lineno", 1))
                if key in reported:
                    continue
                reported.add(key)
                via = " -> ".join(chain) if len(chain) > 1 else mod
                yield graph.modules[mod].finding(
                    node, "jax-free-violation",
                    f"`{declared}` is declared jax-free ({where}) but "
                    f"its top-level import closure reaches jax: {via} "
                    f"-> {target}; make this import lazy "
                    "(function-scope) or remove the module from the "
                    "manifest")


# --------------------------------------------------------------------------
# Rule 5: host-sync-in-jit-helper
# --------------------------------------------------------------------------

@project_rule(
    "host-sync-in-jit-helper",
    "a helper called from a jitted body with a traced argument "
    "fetches it to host — the documented one-call-level blind spot")
def check_host_sync_helper(graph: ProjectGraph,
                           config: PodlintConfig) -> Iterator[Finding]:
    """Call-graph-aware extension of host-sync-in-jit one level into
    helpers.  Only helper parameters that actually receive a traced
    value at the call site are tainted, so trace-time numpy on static
    shapes stays legal."""
    node_to_fid = {id(info.node): fid
                   for fid, info in graph.functions.items()}
    jit_nodes = set()
    for ctx in graph.modules.values():
        for fn, _static in ctx.jit_bodies:
            jit_nodes.add(id(fn))
    seen: set[tuple[str, int, int]] = set()
    for modname, ctx in graph.modules.items():
        for fn, static in ctx.jit_bodies:
            fid = node_to_fid.get(id(fn))
            if fid is None:
                continue
            traced = _param_names(fn) - static
            for e in graph.out_edges.get(fid, ()):
                if e.kind != "call" or not isinstance(e.node, ast.Call):
                    continue
                helper = graph.functions.get(e.callee)
                if helper is None or helper.qualpath == "<module>" \
                        or id(helper.node) in jit_nodes:
                    continue
                call = e.node
                hargs = helper.node.args
                positional = [p.arg for p in (*hargs.posonlyargs,
                                              *hargs.args)]
                tainted: set[str] = set()
                for i, arg in enumerate(call.args):
                    if i < len(positional) and \
                            _rooted_at_param(arg, traced):
                        tainted.add(positional[i])
                for kw in call.keywords:
                    if kw.arg and _rooted_at_param(kw.value, traced):
                        tainted.add(kw.arg)
                tainted -= {"self", "cls"}
                if not tainted:
                    continue
                hctx = graph.modules[helper.modname]
                for n in _own_body_walk(helper.node):
                    if not isinstance(n, ast.Call):
                        continue
                    q = hctx.qual(n.func)
                    bad = None
                    if q in _HOST_FETCH_CALLS and n.args and \
                            _rooted_at_param(n.args[0], tainted):
                        bad = f"{q}()"
                    elif isinstance(n.func, ast.Attribute) and \
                            n.func.attr in _HOST_FETCH_METHODS and \
                            _rooted_at_param(n.func.value, tainted):
                        bad = f".{n.func.attr}()"
                    elif isinstance(n.func, ast.Name) and \
                            n.func.id in _TRACER_COERCIONS and \
                            n.func.id not in hctx.aliases and n.args \
                            and _rooted_at_param(n.args[0], tainted):
                        bad = f"{n.func.id}()"
                    if bad is None:
                        continue
                    key = (helper.fid, n.lineno, n.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield _site_finding(
                        graph, helper.fid, n, "host-sync-in-jit-helper",
                        f"{bad} in helper `{_short(helper.fid)}` "
                        f"fetches a traced value to host — the helper "
                        f"is called from jitted `{fn.name}` "
                        f"({ctx.rel_path}:{call.lineno}) with a traced "
                        "argument; keep the value in jnp or hoist the "
                        "fetch out of the compiled step")
