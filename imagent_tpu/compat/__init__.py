from imagent_tpu.compat.torch_weights import (  # noqa: F401
    convnext_from_torch, convnext_to_torch, resnet_from_torch,
    resnet_to_torch, to_torch_state_dict, vit_from_torch, vit_to_torch,
)
