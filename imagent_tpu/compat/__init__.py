from imagent_tpu.compat.torch_weights import (  # noqa: F401
    resnet_from_torch, resnet_to_torch, vit_from_torch,
)
