"""JAX version compatibility shims.

The framework targets the current ``jax.shard_map`` API (``check_vma``
keyword). Older runtimes (<= 0.4.x) ship it as
``jax.experimental.shard_map.shard_map`` with the keyword named
``check_rep``. Pinning a floor would be the clean answer, but the
deployment story (TPU VMs with preinstalled runtimes; this repo's own
CI image) makes "run on the jax you were handed" the robust one — the
semantic is identical, only the spelling moved.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where available, else the experimental spelling
    with ``check_vma`` mapped onto ``check_rep``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)
