"""torchvision checkpoint import: torch state_dicts → our param trees.

The reference saves ``model.state_dict()`` of a torchvision ResNet
(``imagenet.py:392``, DDP-wrapped so keys carry a ``module.`` prefix).
This module lets a user of the reference bring those checkpoints — or
any torchvision ResNet/ViT weights — into this framework: the converted
tree drops into ``TrainState.params``/``batch_stats`` and the Flax
forward reproduces the torch forward numerically (pinned by
``tests/test_torch_compat.py``, which runs real torch CPU models against
the converted weights).

Pure numpy: accepts any mapping of ``name -> array-like`` (a torch
state_dict works directly; ``.numpy()`` is applied via ``np.asarray``),
no torch import required here.

Layout notes:
* torch conv weight OIHW → Flax HWIO (transpose 2,3,1,0);
* torch Linear weight [out,in] → Flax kernel [in,out];
* BatchNorm weight/bias → scale/bias (params), running_mean/var →
  mean/var (batch_stats);
* ViT fused ``in_proj_weight`` [3D,D] splits into query/key/value
  DenseGeneral kernels [D,H,hd]; ``out_proj`` becomes the [H,hd,D]
  DenseGeneral.
"""

from __future__ import annotations

import numpy as np


def _strip_module(sd: dict) -> dict:
    """Drop DDP's ``module.`` prefix (``imagenet.py:316,392``)."""
    return {k[len("module."):] if k.startswith("module.") else k:
            np.asarray(v) for k, v in sd.items()}


def _conv(w) -> np.ndarray:
    return np.transpose(np.asarray(w), (2, 3, 1, 0))  # OIHW -> HWIO


def _linear(w) -> np.ndarray:
    return np.transpose(np.asarray(w), (1, 0))  # [out,in] -> [in,out]


def resnet_from_torch(state_dict: dict,
                      stage_sizes) -> tuple[dict, dict]:
    """torchvision ResNet state_dict → (params, batch_stats) trees
    matching ``models/resnet.py`` naming. ``stage_sizes`` e.g.
    ``(2, 2, 2, 2)`` for resnet18."""
    sd = _strip_module(state_dict)
    params: dict = {}
    stats: dict = {}

    def put_bn(dst_p: dict, dst_s: dict, name: str, src: str):
        dst_p[name] = {"scale": sd[f"{src}.weight"],
                       "bias": sd[f"{src}.bias"]}
        dst_s[name] = {"mean": sd[f"{src}.running_mean"],
                       "var": sd[f"{src}.running_var"]}

    params["conv1"] = {"kernel": _conv(sd["conv1.weight"])}
    put_bn(params, stats, "bn1", "bn1")

    for i, n_blocks in enumerate(stage_sizes):
        for j in range(n_blocks):
            src = f"layer{i + 1}.{j}"
            name = f"layer{i + 1}_block{j}"
            p: dict = {}
            s: dict = {}
            k = 0
            while f"{src}.conv{k + 1}.weight" in sd:
                p[f"Conv_{k}"] = {
                    "kernel": _conv(sd[f"{src}.conv{k + 1}.weight"])}
                put_bn(p, s, f"BatchNorm_{k}", f"{src}.bn{k + 1}")
                k += 1
            if f"{src}.downsample.0.weight" in sd:
                p["downsample_conv"] = {
                    "kernel": _conv(sd[f"{src}.downsample.0.weight"])}
                put_bn(p, s, "downsample_bn", f"{src}.downsample.1")
            params[name] = p
            stats[name] = s

    params["fc"] = {"kernel": _linear(sd["fc.weight"]),
                    "bias": sd["fc.bias"]}
    return params, stats


def vit_from_torch(state_dict: dict, num_heads: int) -> dict:
    """torchvision ViT (vit_b_16/vit_l_16) state_dict → params tree
    matching ``models/vit.py`` (per-layer encoder, class-token readout).
    Returns params only (ViT has no batch_stats)."""
    sd = _strip_module(state_dict)
    d = sd["class_token"].shape[-1]
    hd = d // num_heads
    params: dict = {
        "conv_proj": {"kernel": _conv(sd["conv_proj.weight"]),
                      "bias": sd["conv_proj.bias"]},
        "class_token": np.asarray(sd["class_token"]).reshape(1, 1, d),
        "pos_embedding": np.asarray(
            sd["encoder.pos_embedding"]).reshape(1, -1, d),
        "ln": {"scale": sd["encoder.ln.weight"],
               "bias": sd["encoder.ln.bias"]},
        "head": {"kernel": _linear(sd["heads.head.weight"]),
                 "bias": sd["heads.head.bias"]},
    }

    i = 0
    while f"encoder.layers.encoder_layer_{i}.ln_1.weight" in sd:
        src = f"encoder.layers.encoder_layer_{i}"
        in_w = np.asarray(sd[f"{src}.self_attention.in_proj_weight"])
        in_b = np.asarray(sd[f"{src}.self_attention.in_proj_bias"])
        qw, kw, vw = np.split(in_w, 3, axis=0)      # each [D, D] (out,in)
        qb, kb, vb = np.split(in_b, 3, axis=0)
        out_w = np.asarray(sd[f"{src}.self_attention.out_proj.weight"])

        def qkv(w, b):
            # [D_out, D_in] -> kernel [D_in, H, hd]; bias [H, hd]
            return {"kernel": _linear(w).reshape(d, num_heads, hd),
                    "bias": b.reshape(num_heads, hd)}

        params[f"encoder_layer_{i}"] = {
            "ln_1": {"scale": sd[f"{src}.ln_1.weight"],
                     "bias": sd[f"{src}.ln_1.bias"]},
            "ln_2": {"scale": sd[f"{src}.ln_2.weight"],
                     "bias": sd[f"{src}.ln_2.bias"]},
            "self_attention": {
                "query": qkv(qw, qb),
                "key": qkv(kw, kb),
                "value": qkv(vw, vb),
                # [D_out, D_in] with D_in = H*hd -> [H, hd, D_out]
                "out": {"kernel": _linear(out_w).reshape(
                    num_heads, hd, d),
                    "bias": sd[f"{src}.self_attention.out_proj.bias"]},
            },
            "mlp_0": {"kernel": _linear(sd[f"{src}.mlp.0.weight"]),
                      "bias": sd[f"{src}.mlp.0.bias"]},
            "mlp_1": {"kernel": _linear(sd[f"{src}.mlp.3.weight"]),
                      "bias": sd[f"{src}.mlp.3.bias"]},
        }
        i += 1
    return params


def vit_to_torch(params: dict) -> dict:
    """The inverse of ``vit_from_torch``: our params tree → a
    torchvision-named ViT ``state_dict`` (numpy values). The per-head
    query/key/value DenseGeneral kernels [D, H, hd] re-fuse into
    torchvision's ``in_proj_weight`` [3D, D] (the QKV re-split inverse),
    and the [H, hd, D] out projection flattens back to [D, H*hd].
    Round-trip is bit-exact (tests/test_torch_compat.py). Completes the
    train-here/serve-in-torch story for the third family alongside
    ``resnet_to_torch``/``convnext_to_torch``.

    Stacked/pipelined ViTs (``models/vit.py stacked=True`` / the
    pipeline layout) carry their encoder weights as one leading-axis-
    stacked ``encoder`` subtree with NO ``encoder_layer_i`` keys — the
    per-layer loop below would silently write a state_dict containing
    only stem/ln/head tensors (strict torch loads fail later; strict=
    False callers silently keep random encoder weights). Refuse before
    writing anything."""
    if "encoder_layer_0" not in params:
        raise ValueError(
            "stacked/pipelined params not supported for torch export: "
            "no 'encoder_layer_0' key (nn.scan layer-stacked layout) — "
            "convert to the per-layer layout first, or train/export "
            "with the unstacked model")
    d = np.asarray(params["class_token"]).shape[-1]
    sd: dict = {
        "conv_proj.weight": _conv_inv(params["conv_proj"]["kernel"]),
        "conv_proj.bias": np.asarray(params["conv_proj"]["bias"]),
        "class_token": np.asarray(params["class_token"]).reshape(1, 1, d),
        "encoder.pos_embedding": np.asarray(
            params["pos_embedding"]).reshape(1, -1, d),
        "encoder.ln.weight": np.asarray(params["ln"]["scale"]),
        "encoder.ln.bias": np.asarray(params["ln"]["bias"]),
        "heads.head.weight": _linear_inv(params["head"]["kernel"]),
        "heads.head.bias": np.asarray(params["head"]["bias"]),
    }

    def qkv_inv(p: dict) -> tuple[np.ndarray, np.ndarray]:
        # kernel [D_in, H, hd] -> [D_out, D_in] (inverse of `qkv` in
        # vit_from_torch); bias [H, hd] -> [D_out]
        k = np.asarray(p["kernel"])
        d_in = k.shape[0]
        return (_linear_inv(k.reshape(d_in, -1)),
                np.asarray(p["bias"]).reshape(-1))

    i = 0
    while f"encoder_layer_{i}" in params:
        src = params[f"encoder_layer_{i}"]
        dst = f"encoder.layers.encoder_layer_{i}"
        qw, qb = qkv_inv(src["self_attention"]["query"])
        kw, kb = qkv_inv(src["self_attention"]["key"])
        vw, vb = qkv_inv(src["self_attention"]["value"])
        sd[f"{dst}.self_attention.in_proj_weight"] = np.concatenate(
            [qw, kw, vw], axis=0)
        sd[f"{dst}.self_attention.in_proj_bias"] = np.concatenate(
            [qb, kb, vb], axis=0)
        out_k = np.asarray(src["self_attention"]["out"]["kernel"])
        sd[f"{dst}.self_attention.out_proj.weight"] = _linear_inv(
            out_k.reshape(-1, out_k.shape[-1]))
        sd[f"{dst}.self_attention.out_proj.bias"] = np.asarray(
            src["self_attention"]["out"]["bias"])
        sd[f"{dst}.ln_1.weight"] = np.asarray(src["ln_1"]["scale"])
        sd[f"{dst}.ln_1.bias"] = np.asarray(src["ln_1"]["bias"])
        sd[f"{dst}.ln_2.weight"] = np.asarray(src["ln_2"]["scale"])
        sd[f"{dst}.ln_2.bias"] = np.asarray(src["ln_2"]["bias"])
        sd[f"{dst}.mlp.0.weight"] = _linear_inv(src["mlp_0"]["kernel"])
        sd[f"{dst}.mlp.0.bias"] = np.asarray(src["mlp_0"]["bias"])
        sd[f"{dst}.mlp.3.weight"] = _linear_inv(src["mlp_1"]["kernel"])
        sd[f"{dst}.mlp.3.bias"] = np.asarray(src["mlp_1"]["bias"])
        i += 1
    return sd


def to_torch_state_dict(arch: str, params: dict,
                        batch_stats: dict | None = None) -> dict:
    """Arch-dispatched export: our trees → a torchvision-named
    ``state_dict`` (numpy values) for any supported ``--arch``. Used by
    the CLI ``--export-torch`` flag (engine.run) and usable directly.
    The inverse of what ``--init-from-torch`` accepts, minus the DDP
    ``module.`` prefix (torchvision-loadable, ``imagenet.py:392``)."""
    if arch.startswith("vit"):
        return vit_to_torch(params)
    if arch.startswith("convnext"):
        return convnext_to_torch(params)
    from imagent_tpu.models.resnet import STAGE_SIZES

    if arch not in STAGE_SIZES:
        raise ValueError(f"no torch export for arch {arch!r}")
    return resnet_to_torch(params, batch_stats or {}, STAGE_SIZES[arch])


def _conv_inv(k) -> np.ndarray:
    return np.transpose(np.asarray(k), (3, 2, 0, 1))  # HWIO -> OIHW


def _linear_inv(k) -> np.ndarray:
    return np.transpose(np.asarray(k), (1, 0))  # [in,out] -> [out,in]


def resnet_to_torch(params: dict, batch_stats: dict,
                    stage_sizes) -> dict:
    """The inverse of ``resnet_from_torch``: our param/batch_stats trees
    → a torchvision-named ResNet ``state_dict`` (numpy values; pass
    through ``torch.from_numpy``/``torch.save`` as desired).

    Gives reference users a two-way street: train here, keep serving or
    analyzing with their existing torch tooling. ``num_batches_tracked``
    is emitted as 0 (our BN momentum is torch-equivalent but we don't
    count batches; torchvision loads fine either way). Round-trip is
    bit-exact (tests/test_torch_compat.py)."""
    stats = batch_stats
    sd: dict = {}

    def put_bn(dst: str, p: dict, s: dict):
        sd[f"{dst}.weight"] = np.asarray(p["scale"])
        sd[f"{dst}.bias"] = np.asarray(p["bias"])
        sd[f"{dst}.running_mean"] = np.asarray(s["mean"])
        sd[f"{dst}.running_var"] = np.asarray(s["var"])
        sd[f"{dst}.num_batches_tracked"] = np.asarray(0, np.int64)

    sd["conv1.weight"] = _conv_inv(params["conv1"]["kernel"])
    put_bn("bn1", params["bn1"], stats["bn1"])

    for i, n_blocks in enumerate(stage_sizes):
        for j in range(n_blocks):
            name = f"layer{i + 1}_block{j}"
            dst = f"layer{i + 1}.{j}"
            p, s = params[name], stats[name]
            k = 0
            while f"Conv_{k}" in p:
                sd[f"{dst}.conv{k + 1}.weight"] = _conv_inv(
                    p[f"Conv_{k}"]["kernel"])
                put_bn(f"{dst}.bn{k + 1}", p[f"BatchNorm_{k}"],
                       s[f"BatchNorm_{k}"])
                k += 1
            if "downsample_conv" in p:
                sd[f"{dst}.downsample.0.weight"] = _conv_inv(
                    p["downsample_conv"]["kernel"])
                put_bn(f"{dst}.downsample.1", p["downsample_bn"],
                       s["downsample_bn"])

    sd["fc.weight"] = _linear_inv(params["fc"]["kernel"])
    sd["fc.bias"] = np.asarray(params["fc"]["bias"])
    return sd


def convnext_from_torch(state_dict: dict) -> dict:
    """torchvision ConvNeXt (convnext_tiny/small/base/large) state_dict
    → params tree matching ``models/convnext.py``. Structure is inferred
    from the keys (torchvision's ``features`` indices: 0 = stem,
    odd = block stages, even = LayerNorm+conv downsamples; CNBlock
    submodule indices: block.0 dwconv, block.2 LayerNorm, block.3/5 the
    two Linears, plus the ``layer_scale`` parameter). ConvNeXt has no
    BatchNorm, so there is no batch_stats tree to return."""
    sd = _strip_module(state_dict)
    params: dict = {
        "stem_conv": {"kernel": _conv(sd["features.0.0.weight"]),
                      "bias": sd["features.0.0.bias"]},
        "stem_norm": {"scale": sd["features.0.1.weight"],
                      "bias": sd["features.0.1.bias"]},
        "head_norm": {"scale": sd["classifier.0.weight"],
                      "bias": sd["classifier.0.bias"]},
        "head": {"kernel": _linear(sd["classifier.2.weight"]),
                 "bias": sd["classifier.2.bias"]},
    }
    stage = 0
    f = 1  # features index: odd entries are stages, even are downsamples
    while f"features.{f}.0.block.0.weight" in sd:
        j = 0
        while f"features.{f}.{j}.block.0.weight" in sd:
            src = f"features.{f}.{j}"
            params[f"stage{stage}_block{j}"] = {
                "dwconv": {"kernel": _conv(sd[f"{src}.block.0.weight"]),
                           "bias": sd[f"{src}.block.0.bias"]},
                "norm": {"scale": sd[f"{src}.block.2.weight"],
                         "bias": sd[f"{src}.block.2.bias"]},
                "pwconv1": {"kernel": _linear(sd[f"{src}.block.3.weight"]),
                            "bias": sd[f"{src}.block.3.bias"]},
                "pwconv2": {"kernel": _linear(sd[f"{src}.block.5.weight"]),
                            "bias": sd[f"{src}.block.5.bias"]},
                "layer_scale": np.asarray(
                    sd[f"{src}.layer_scale"]).reshape(-1),
            }
            j += 1
        stage += 1
        f += 1
        if f"features.{f}.0.weight" in sd:  # downsample: LN then conv
            params[f"downsample{stage}_norm"] = {
                "scale": sd[f"features.{f}.0.weight"],
                "bias": sd[f"features.{f}.0.bias"]}
            params[f"downsample{stage}_conv"] = {
                "kernel": _conv(sd[f"features.{f}.1.weight"]),
                "bias": sd[f"features.{f}.1.bias"]}
            f += 1
    return params


def convnext_to_torch(params: dict) -> dict:
    """The inverse of ``convnext_from_torch``: our params tree → a
    torchvision-named ConvNeXt ``state_dict`` (numpy values). Round-trip
    is bit-exact (tests/test_torch_compat.py)."""
    sd: dict = {
        "features.0.0.weight": _conv_inv(params["stem_conv"]["kernel"]),
        "features.0.0.bias": np.asarray(params["stem_conv"]["bias"]),
        "features.0.1.weight": np.asarray(params["stem_norm"]["scale"]),
        "features.0.1.bias": np.asarray(params["stem_norm"]["bias"]),
        "classifier.0.weight": np.asarray(params["head_norm"]["scale"]),
        "classifier.0.bias": np.asarray(params["head_norm"]["bias"]),
        "classifier.2.weight": _linear_inv(params["head"]["kernel"]),
        "classifier.2.bias": np.asarray(params["head"]["bias"]),
    }
    stage = 0
    f = 1
    while f"stage{stage}_block0" in params:
        j = 0
        while f"stage{stage}_block{j}" in params:
            b = params[f"stage{stage}_block{j}"]
            dst = f"features.{f}.{j}"
            sd[f"{dst}.block.0.weight"] = _conv_inv(b["dwconv"]["kernel"])
            sd[f"{dst}.block.0.bias"] = np.asarray(b["dwconv"]["bias"])
            sd[f"{dst}.block.2.weight"] = np.asarray(b["norm"]["scale"])
            sd[f"{dst}.block.2.bias"] = np.asarray(b["norm"]["bias"])
            sd[f"{dst}.block.3.weight"] = _linear_inv(
                b["pwconv1"]["kernel"])
            sd[f"{dst}.block.3.bias"] = np.asarray(b["pwconv1"]["bias"])
            sd[f"{dst}.block.5.weight"] = _linear_inv(
                b["pwconv2"]["kernel"])
            sd[f"{dst}.block.5.bias"] = np.asarray(b["pwconv2"]["bias"])
            sd[f"{dst}.layer_scale"] = np.asarray(
                b["layer_scale"]).reshape(-1, 1, 1)
            j += 1
        stage += 1
        f += 1
        if f"downsample{stage}_norm" in params:
            sd[f"features.{f}.0.weight"] = np.asarray(
                params[f"downsample{stage}_norm"]["scale"])
            sd[f"features.{f}.0.bias"] = np.asarray(
                params[f"downsample{stage}_norm"]["bias"])
            sd[f"features.{f}.1.weight"] = _conv_inv(
                params[f"downsample{stage}_conv"]["kernel"])
            sd[f"features.{f}.1.bias"] = np.asarray(
                params[f"downsample{stage}_conv"]["bias"])
            f += 1
    return sd
